"""SLO-driven elastic serving control plane (autoscale + drain + evict).

The closed loop the sensors and actuators of PRs 6/7/9/10/11 were built
for: a rank-0 controller that *samples* the MetricsRegistry SLO signals
(queue depth, windowed TTFT p99, batch occupancy), *decides* through the
:class:`~horovod_tpu.serving.policy.ScalePolicy` hysteresis/cooldown
policy, and *acts* by resizing the tensor-parallel decode mesh through
the same :func:`horovod_tpu.elastic.run_loop.apply_resize` sequence the
training loop runs after a re-rendezvous.

Transitions are graceful by construction:

* **drain** -- admission pauses, every in-flight slot flips to the
  ``draining`` lifecycle state, and the old mesh keeps decoding for a
  bounded step budget so near-done requests finish with bit-identical
  tokens (the completion path);
* **suspend + re-prefill** -- survivors of the budget are suspended
  (progress = prompt + emitted tokens, KV pages freed exactly) and
  re-prefilled on the post-resize mesh, continuing within sampling
  tolerance (the re-prefill path);
* **eviction** -- a ``kill@`` dead rank forces an immediate resize onto
  the survivors, and a ``slow@`` rank is evicted automatically when the
  :class:`~horovod_tpu.timeline.straggler.StragglerMonitor` lateness
  EWMA crosses ``HOROVOD_CTL_EVICT_LATENESS_S`` (the monitor's eviction
  hook latches the candidate; the policy consumes it).

Every decision lands in the ``horovod_ctl_*`` metric families and as a
span-tagged timeline event (kind ``ctl``, legs ``ctl/<action>/...``), so
the merged Perfetto trace shows *why* the fleet resized, next to the
per-leg decode spans showing *what* it cost.

Chaos faults are interpreted **virtually** over the controller's virtual
ranks: the spec grammar and rank=any resolution are
:class:`~horovod_tpu.elastic.chaos.ChaosInjector`'s own, but ``kill``
marks the device dead instead of ``os._exit`` (one process emulates the
fleet, exactly like ``examples/straggler_probe.py``) and ``slow``
inflates the rank's synthesized step-wall summaries feeding the monitor.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..timeline import metrics as _metrics
from ..timeline import spans as _spans
from ..timeline.straggler import StragglerMonitor
from .engine import ServingEngine, ServingReport, _pct
from .policy import (Decision, PolicyConfig, ScalePolicy, SLOSample,
                     valid_tp_sizes)
from .scheduler import Request

__all__ = ["ServingControlPlane", "ControlPlaneReport", "FleetScaler"]


class _VirtualFaults:
    """Chaos-spec adapter for the single-process serving drill.

    Reuses the injector's parser and deterministic ``rank=any``
    resolution but never calls ``on_step`` -- a real ``kill`` fault
    would ``os._exit(137)`` the *controller*.  Faults are keyed on the
    decode-step index and handed back to the control plane to fire
    virtually.
    """

    def __init__(self, spec: Optional[str], world: int):
        self.faults: list = []
        if spec:
            from ..elastic.chaos import ChaosInjector
            # rank=-1 matches no fault, so even an accidental on_step
            # call could never fire for real.
            self.faults = ChaosInjector(spec, rank=-1, size=world).faults

    def due(self, step: int) -> list:
        out = [f for f in self.faults if not f.fired and f.step <= step]
        for f in out:
            f.fired = True
        return out


class _MeshResizeState:
    """Duck-typed elastic ``State`` carrier handed to ``apply_resize``:
    ``resize`` swaps the serving mesh, ``on_reset`` restores suspended
    requests and re-opens admission.  No training carry anywhere."""

    def __init__(self, plane: "ServingControlPlane"):
        self._plane = plane

    def resize(self, old_size: int, new_size: int):
        return self._plane._do_resize(old_size, new_size)

    def on_reset(self) -> None:
        self._plane._on_reset()


@dataclasses.dataclass
class ControlPlaneReport:
    """One drill's closed-loop outcome, wrapped around the serving
    report.  ``lost_requests`` must be 0: every admissible request
    either completed on the mesh it started on or was re-prefilled and
    completed on a later one."""

    serving: ServingReport
    mesh_size_initial: int
    mesh_size_final: int
    decisions: List[dict]
    decision_counts: Dict[str, int]
    resizes: int
    evicted_ranks: List[int]
    dead_ranks: List[int]
    drained_completed: int
    drained_reprefilled: int
    drain_leaked_pages: int
    slo_violation_s: float
    lost_requests: int

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["serving"] = self.serving.as_dict()
        return d


class ServingControlPlane:
    """Autoscaling controller wrapped around one :class:`ServingEngine`.

    ``devices`` is the virtual fleet (defaults to ``jax.devices()``);
    the decode mesh is always the first ``size`` *healthy* devices, so
    kills and evictions shrink the usable pool and the policy ladder
    adapts.  ``policy`` may be any object with ``decide(sample)`` /
    ``mark_applied(decision, now_s)`` -- tests script it.
    """

    def __init__(self, config, params, *, devices=None,
                 initial_tp: Optional[int] = None,
                 policy=None, policy_config: Optional[PolicyConfig] = None,
                 monitor: Optional[StragglerMonitor] = None,
                 chaos_spec: Optional[str] = None, **engine_kwargs):
        self.config = config
        self.devices = list(devices if devices is not None
                            else jax.devices())
        self.policy_cfg = policy_config or PolicyConfig.from_env()
        sizes = valid_tp_sizes(config, len(self.devices))
        self.policy = policy if policy is not None else ScalePolicy(
            self.policy_cfg, sizes)
        allowed = [s for s in sizes
                   if self.policy_cfg.min_tp <= s <= self.policy_cfg.max_tp]
        if initial_tp is None:
            initial_tp = allowed[-1] if allowed else sizes[-1]
        self.healthy: List[int] = list(range(len(self.devices)))
        self.mesh_ranks: List[int] = self.healthy[:initial_tp]
        self.dead: set = set()
        self.evicted: List[int] = []
        self.engine = ServingEngine(config, params,
                                    mesh=self._mesh(self.mesh_ranks),
                                    **engine_kwargs)
        self.monitor = monitor if monitor is not None else StragglerMonitor(
            world=len(self.devices))
        self.monitor.add_eviction_hook(self.policy_cfg.evict_lateness_s,
                                       self._note_evict_candidate)
        self._evict_candidate: Optional[Tuple[int, float]] = None
        self._faults = _VirtualFaults(chaos_spec, len(self.devices))
        self._slow: Dict[int, float] = {}   # rank -> per-step inflation
        self._handled_dead: set = set()
        self._pending: Optional[Tuple[List[int], List[Request]]] = None
        self._monitor_warmup = 1  # skip the compile-dominated first step

        reg = _metrics.registry()
        self._m_decisions = reg.counter(
            "horovod_ctl_decisions_total",
            "Serving control-plane decisions by action",
            labelnames=("action",))
        self._m_resizes = reg.counter(
            "horovod_ctl_resizes_total",
            "Decode-mesh resizes executed by the control plane",
            labelnames=("direction",))
        self._m_evictions = reg.counter(
            "horovod_ctl_evictions_total",
            "Ranks removed from the serving fleet by the control plane",
            labelnames=("reason",))
        self._m_drained = reg.counter(
            "horovod_ctl_drained_requests_total",
            "In-flight requests carried through a resize, by drain path",
            labelnames=("path",))
        self._m_violation = reg.counter(
            "horovod_ctl_slo_violation_seconds_total",
            "Seconds the sampled SLO (TTFT p99 / queue depth) was in "
            "violation")
        self._m_mesh_size = reg.gauge(
            "horovod_ctl_mesh_size",
            "Current decode-mesh tensor-parallel size")
        self._m_healthy = reg.gauge(
            "horovod_ctl_healthy_ranks",
            "Devices the control plane still considers usable")
        self._m_ttft_p99 = reg.gauge(
            "horovod_ctl_ttft_p99_seconds",
            "Windowed TTFT p99 as sampled by the control plane")
        self._m_prefix_hit = reg.gauge(
            "horovod_ctl_prefix_hit_rate",
            "Radix prefix-cache hit rate as sampled by the control "
            "plane (0 when the cache is off)")
        self._m_mesh_size.set(len(self.mesh_ranks))
        self._m_healthy.set(len(self.healthy))

        # Drill bookkeeping (reset per serve()).
        self.decisions: List[dict] = []
        self._stats: Dict[str, Any] = {}

    # -- mesh helpers ------------------------------------------------------
    def _mesh(self, ranks: Sequence[int]):
        from jax.sharding import Mesh
        devs = [self.devices[r] for r in ranks]
        return Mesh(np.asarray(devs, dtype=object).reshape(len(devs)),
                    ("tp",))

    # -- monitor hook ------------------------------------------------------
    def _note_evict_candidate(self, rank: int, lateness_s: float) -> None:
        self._evict_candidate = (int(rank), float(lateness_s))

    # -- chaos (virtual firing) --------------------------------------------
    def _fire_faults(self, step: int, now_s: float) -> None:
        rec = _spans.recorder()
        for f in self._faults.due(step):
            _metrics.registry().counter(
                "horovod_chaos_faults_total",
                "Faults fired by the chaos injector").inc()
            rec.add("ctl", 0.0, leg=f"ctl/fault/{f.kind}")
            if f.kind == "kill":
                if f.rank in self.healthy:
                    self.healthy.remove(f.rank)
                self.dead.add(f.rank)
                self._slow.pop(f.rank, None)
                # Forget its EWMA now: a dead rank stops reporting, and
                # a frozen stale EWMA would otherwise read as lateness.
                self.monitor.evict(f.rank)
                self._m_healthy.set(len(self.healthy))
            elif f.kind == "slow":
                # A degraded device, not a hiccup: the rank stays slow
                # until the monitor's EWMA gets it evicted.
                self._slow[f.rank] = float(f.secs)

    def _feed_monitor(self, step: int, step_s: float) -> None:
        if self._monitor_warmup > 0:
            # The first step on a (re)built mesh is compile-dominated;
            # its wall says nothing about rank behavior.
            self._monitor_warmup -= 1
            return
        for r in self.mesh_ranks:
            if r in self.dead:
                continue  # a dead rank publishes nothing
            self.monitor.observe({
                "rank": r, "step": step, "t0_us": 0.0,
                "wall_s": step_s + self._slow.get(r, 0.0),
                "spans": {}, "legs": {}})

    # -- decode step (shared by the main loop and the drain) ---------------
    def _decode_once(self, now) -> float:
        # Delegates to the engine's shared round so occupancy/TTFT
        # bookkeeping stays truthful whatever the engine's decode mode
        # is (plain or speculative).  The DRAIN path always runs plain
        # decode: a draining mesh is about to lose ranks and the verify
        # step's wider dispatch buys nothing on the way down.
        return self.engine.decode_once(self._stats, now)

    # -- controller tick ---------------------------------------------------
    def _sample(self, now_s: float) -> SLOSample:
        sched = self.engine.scheduler
        p99 = None
        snap_fn = getattr(sched._m_ttft, "snapshot", None)
        if snap_fn is not None:
            curr = snap_fn()
            win = _metrics.histogram_window(curr, self._stats["ttft_base"])
            self._stats["ttft_base"] = curr
            p99 = _metrics.histogram_quantile(win, 0.99)
        prefix = getattr(self.engine, "_prefix", None)
        hit_rate = prefix.hit_rate if prefix is not None else None
        return SLOSample(
            now_s=now_s, queue_depth=len(sched.queue), ttft_p99_s=p99,
            occupancy=sched.occupancy, mesh_size=len(self.mesh_ranks),
            mesh_ranks=tuple(self.mesh_ranks),
            healthy=tuple(self.healthy),
            dead_ranks=tuple(sorted(self.dead)),
            evict_candidate=self._evict_candidate,
            prefix_hit_rate=hit_rate)

    def _tick(self, now) -> None:
        now_s = now()
        st = self._stats
        if now_s - st["last_tick"] < self.policy_cfg.interval_s:
            return
        sample = self._sample(now_s)
        self._m_ttft_p99.set(sample.ttft_p99_s or 0.0)
        self._m_prefix_hit.set(sample.prefix_hit_rate or 0.0)
        violated = (sample.queue_depth >= self.policy_cfg.queue_high
                    or (sample.ttft_p99_s is not None
                        and sample.ttft_p99_s > self.policy_cfg.ttft_slo_s))
        if violated:
            dt = max(now_s - st["last_tick"], 0.0)
            st["slo_violation_s"] += dt
            self._m_violation.inc(dt)
        st["last_tick"] = now_s

        decision = self.policy.decide(sample)
        self._m_decisions.labels(action=decision.action).inc()
        self.decisions.append({
            "step": st["decode_steps"], "now_s": round(now_s, 4),
            "action": decision.action, "reason": decision.reason,
            "target_size": decision.target_size,
            "evict_rank": decision.evict_rank})
        rec = _spans.recorder()
        self._evict_candidate = None  # consumed by this decision
        if decision.is_hold:
            rec.add("ctl", 0.0, leg="ctl/hold")
            return
        with rec.span("ctl", name=f"decision:{decision.action}",
                      leg=f"ctl/{decision.action}/{decision.reason}"):
            self._apply(decision, now)
        self.policy.mark_applied(decision, now_s)

    # -- decision execution ------------------------------------------------
    def _apply(self, decision: Decision, now) -> None:
        if decision.evict_rank is not None:
            r = decision.evict_rank
            if r in self.healthy:
                self.healthy.remove(r)
            self.evicted.append(r)
            self.monitor.evict(r)
            self._slow.pop(r, None)
            self._m_evictions.labels(reason="straggler").inc()
            self._m_healthy.set(len(self.healthy))
        if decision.reason.startswith("rank-dead"):
            for r in sorted(self.dead - self._handled_dead):
                self._handled_dead.add(r)
                self.monitor.evict(r)
                self._m_evictions.labels(reason="dead").inc()
        # A dead rank invalidates the old mesh: no completion drain, go
        # straight to suspend + re-prefill on the survivors.  Growth
        # should add capacity now, not after a drain.  Only a voluntary
        # shrink (and a straggler eviction, whose old mesh is merely
        # slow) earns the completion budget.
        hard = decision.reason.startswith("rank-dead")
        budget = 0 if (hard or decision.action == "grow") \
            else self.policy_cfg.drain_steps
        self._transition(decision, now, drain_budget=budget,
                         decode_ok=not hard)

    def _transition(self, decision: Decision, now, *,
                    drain_budget: int, decode_ok: bool) -> None:
        eng = self.engine
        sched = eng.scheduler
        st = self._stats
        old_ranks = list(self.mesh_ranks)
        new_ranks = self.healthy[:decision.target_size]

        sched.pause_admission()
        for slot in list(sched.active):
            sched.mark_draining(slot)
        done_before = len(st["completed"])
        steps = 0
        while sched.active and decode_ok and steps < drain_budget:
            self._decode_once(now)
            steps += 1
        finished = len(st["completed"]) - done_before
        st["drained_completed"] += finished
        if finished:
            self._m_drained.labels(path="completed").inc(finished)

        suspended = [sched.suspend(slot)
                     for slot in sorted(sched.active)]
        st["drained_reprefilled"] += len(suspended)
        # Exact-release check: suspension freed every slot's pages, so
        # a sweep over the old pool must recover nothing.
        st["drain_leaked_pages"] += eng.cache.release_all()

        self._pending = (new_ranks, suspended)
        from ..elastic.run_loop import apply_resize
        if len(new_ranks) == len(old_ranks):
            # Same size, different devices (a spare replaced a dead or
            # evicted rank): apply_resize's size gate would skip the
            # swap, so rebuild first; it still runs on_reset.
            self._rebuild(new_ranks, direction="swap")
        apply_resize(_MeshResizeState(self), len(old_ranks),
                     len(new_ranks))

    def _do_resize(self, old_size: int, new_size: int) -> str:
        new_ranks, _ = self._pending
        direction = "grow" if new_size > old_size else "shrink"
        self._rebuild(new_ranks, direction=direction)
        return (f"serving mesh {direction} {old_size} -> {new_size} "
                f"(ranks {list(new_ranks)})")

    def _rebuild(self, new_ranks: List[int], *, direction: str) -> None:
        # Ranks leaving the mesh stop reporting; forget their EWMAs so
        # a stale-fast spare doesn't inflate everyone else's lateness
        # (and a stale-slow one doesn't read as a straggler forever).
        for r in set(self.mesh_ranks) - set(new_ranks):
            self.monitor.evict(r)
        self.mesh_ranks = list(new_ranks)
        self.engine.rebuild_mesh(self._mesh(new_ranks))
        self._monitor_warmup = 1  # next step pays the recompile
        self._m_resizes.labels(direction=direction).inc()
        self._m_mesh_size.set(len(new_ranks))
        self._stats["resizes"] += 1

    def _on_reset(self) -> None:
        if self._pending is None:
            return
        _, suspended = self._pending
        self._pending = None
        eng = self.engine
        sched = eng.scheduler
        st = self._stats
        for req in suspended:
            slot = sched.restore(req)
            st["last_tokens"][slot] = eng.re_prefill(slot, req)
            st["adapter_ids"][slot] = req.adapter_id
            self._m_drained.labels(path="reprefill").inc()
        sched.resume_admission()

    # -- the closed loop ---------------------------------------------------
    def serve(self, requests: Sequence[Request]) -> ControlPlaneReport:
        eng = self.engine
        sched = eng.scheduler
        mesh_size_initial = len(self.mesh_ranks)
        pending = sorted(requests, key=lambda r: r.arrival_s)
        rejected = 0
        waiting: List[Request] = []
        for req in pending:
            if req.prompt_len + req.max_new_tokens > eng.max_len:
                rejected += 1
                sched._m_requests.labels(event="rejected").inc()
            else:
                waiting.append(req)

        start = time.monotonic()
        skip = [0.0]

        def now() -> float:
            return time.monotonic() - start + skip[0]

        snap_fn = getattr(sched._m_ttft, "snapshot", None)
        self.decisions = []
        self._stats = {
            "completed": [], "occ_samples": [], "decode_steps": 0,
            "last_tokens": np.zeros((eng.slots,), np.int32),
            "adapter_ids": np.zeros((eng.slots,), np.int32),
            "last_tick": 0.0, "slo_violation_s": 0.0,
            "drained_completed": 0, "drained_reprefilled": 0,
            "drain_leaked_pages": 0, "resizes": 0,
            "ttft_base": snap_fn() if snap_fn is not None else None,
        }
        st = self._stats
        i = 0

        while True:
            while i < len(waiting) and waiting[i].arrival_s <= now():
                sched.submit(waiting[i])
                i += 1
            if not sched.has_work():
                if i >= len(waiting):
                    break
                gap = waiting[i].arrival_s - now()
                if gap > 0:
                    skip[0] += gap
                self._tick(now)
                continue

            for slot, req in sched.admit(now()):
                first = eng._do_prefill(
                    slot, req, jnp.asarray(req.prompt, jnp.int32))
                req.tokens.append(first)
                sched.note_prefill(req, now())
                st["last_tokens"][slot] = first
                st["adapter_ids"][slot] = req.adapter_id
                if req.finished:
                    st["completed"].append(sched.release(slot, now()))

            if sched.active:
                step = st["decode_steps"] + 1
                self._fire_faults(step, now())
                step_s = self._decode_once(now)
                self._feed_monitor(step, step_s)
            self._tick(now)

        wall_s = max(time.monotonic() - start, 1e-9)
        completed = st["completed"]
        new_tokens = sum(len(r.tokens) for r in completed)
        ttfts = [r.ttft_s for r in completed if r.ttft_s is not None]
        lats = [l for r in completed for l in r.token_latencies]
        serving = ServingReport(
            num_requests=len(requests), completed=len(completed),
            rejected=rejected,
            prompt_tokens=sum(r.prompt_len for r in completed),
            new_tokens=new_tokens, wall_s=wall_s,
            decode_steps=st["decode_steps"],
            tokens_per_s=new_tokens / wall_s,
            ttft_p50_s=_pct(ttfts, 50), ttft_p99_s=_pct(ttfts, 99),
            token_latency_p50_s=_pct(lats, 50),
            token_latency_p99_s=_pct(lats, 99),
            mean_occupancy=(float(np.mean(st["occ_samples"]))
                            if st["occ_samples"] else 0.0))
        counts: Dict[str, int] = {}
        for d in self.decisions:
            counts[d["action"]] = counts.get(d["action"], 0) + 1
        return ControlPlaneReport(
            serving=serving,
            mesh_size_initial=mesh_size_initial,
            mesh_size_final=len(self.mesh_ranks),
            decisions=list(self.decisions),
            decision_counts=counts,
            resizes=st["resizes"],
            evicted_ranks=list(self.evicted),
            dead_ranks=sorted(self.dead),
            drained_completed=st["drained_completed"],
            drained_reprefilled=st["drained_reprefilled"],
            drain_leaked_pages=st["drain_leaked_pages"],
            slo_violation_s=st["slo_violation_s"],
            lost_requests=(len(requests) - rejected - len(completed)))


class FleetScaler:
    """Grow-by-adding-capacity controller for a disaggregated fleet.

    The per-engine :class:`ServingControlPlane` resizes ONE engine's tp
    mesh; the fleet scaler watches the SAME SLO signals summed across
    every decode engine and, on a sustained breach, asks the fleet to
    commission a whole new decode engine under live traffic
    (``fleet.add_decode_worker``).  The fleet object is duck-typed --
    anything with ``schedulers()`` (name -> Scheduler), ``num_engines``
    and ``add_decode_worker(reason)`` works -- so this module never
    imports :mod:`.fleet` (which imports us for exactly this class).

    TTFT p99 is windowed fleet-wide: all engines observe into the one
    shared ``horovod_serving_ttft_seconds`` histogram, and the scaler
    keeps its own snapshot base so each tick sees only the TTFTs that
    landed since the previous tick (the ``ServingControlPlane._sample``
    pattern).
    """

    def __init__(self, fleet, policy: Optional["FleetPolicy"] = None):
        from .policy import FleetPolicy
        self.fleet = fleet
        self.policy = policy or FleetPolicy()
        self.decisions: List[dict] = []
        self.slo_violation_s = 0.0
        self._last_tick = 0.0
        self._ttft_base: Any = None
        reg = _metrics.registry()
        self._m_decisions = reg.counter(
            "horovod_fleet_decisions_total",
            "Fleet scaler decisions by action", labelnames=("action",))
        self._m_violation = reg.counter(
            "horovod_fleet_slo_violation_seconds_total",
            "Cumulative seconds the fleet spent outside its SLO")
        self._m_ttft_p99 = reg.gauge(
            "horovod_fleet_ttft_p99_seconds",
            "Fleet-wide windowed TTFT p99 seen by the scaler")

    def _fleet_p99(self) -> Optional[float]:
        scheds = list(self.fleet.schedulers().values())
        if not scheds:
            return None
        snap_fn = getattr(scheds[0]._m_ttft, "snapshot", None)
        if snap_fn is None:
            return None
        curr = snap_fn()
        win = _metrics.histogram_window(curr, self._ttft_base)
        self._ttft_base = curr
        return _metrics.histogram_quantile(win, 0.99)

    def sample(self, now_s: float) -> "FleetSample":
        from .policy import FleetSample
        scheds = self.fleet.schedulers()
        queued = sum(len(s.queue) for s in scheds.values())
        occ = (float(np.mean([s.occupancy for s in scheds.values()]))
               if scheds else 0.0)
        p99 = self._fleet_p99()
        self._m_ttft_p99.set(p99 or 0.0)
        return FleetSample(now_s=now_s, queue_depth=queued,
                           ttft_p99_s=p99, occupancy=occ,
                           engines=self.fleet.num_engines)

    def tick(self, now_s: float) -> Decision:
        cfg = self.policy.config
        if now_s - self._last_tick < cfg.interval_s:
            return Decision("hold", "interval")
        sample = self.sample(now_s)
        violated = (sample.queue_depth >= cfg.queue_high
                    or (sample.ttft_p99_s is not None
                        and sample.ttft_p99_s > cfg.ttft_slo_s))
        if violated:
            dt = max(now_s - self._last_tick, 0.0)
            self.slo_violation_s += dt
            self._m_violation.inc(dt)
        self._last_tick = now_s

        decision = self.policy.decide(sample)
        self._m_decisions.labels(action=decision.action).inc()
        self.decisions.append({
            "now_s": round(now_s, 4), "action": decision.action,
            "reason": decision.reason,
            "target_size": decision.target_size,
            "queue_depth": sample.queue_depth,
            "ttft_p99_s": sample.ttft_p99_s})
        if decision.is_hold:
            return decision
        rec = _spans.recorder()
        with rec.span("ctl", name=f"fleet:{decision.action}",
                      leg=f"ctl/{decision.action}/{decision.reason}"):
            self.fleet.add_decode_worker(decision.reason)
        self.policy.mark_applied(decision, now_s)
        return decision
