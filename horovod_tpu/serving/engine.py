"""Serving engine front-end: prefetch -> prefill -> continuous decode.

One object owns the whole data plane: the paged KV cache, the
continuous-batching scheduler, the jitted prefill, and the
tensor-parallel decode step.  The request feed generalizes the
``data.DevicePrefetcher`` double-buffering idiom from training batches
to requests: a producer thread stages each upcoming prompt onto device
while the engine is still decoding, so admission never stalls on a
host-to-device copy.

Knobs (all overridable per-constructor-arg, documented in docs/api.md):

* ``HOROVOD_SERVING_SLOTS`` -- decode batch slots (default 8)
* ``HOROVOD_SERVING_PAGE_SIZE`` -- KV page length in tokens (default 16)
* ``HOROVOD_SERVING_MAX_LEN`` -- per-sequence cap (default: model max)
* ``HOROVOD_SERVING_PREFETCH`` -- request prefetch depth (default 2)
* ``HOROVOD_SPEC_DECODE`` -- speculative decoding on/off (default off)
* ``HOROVOD_SPEC_K`` -- draft tokens per speculative round (default 4)
* ``HOROVOD_PREFILL_CHUNK`` -- chunked-prefill chunk length in tokens
  (default 0 = whole-prompt prefill)
* ``HOROVOD_KV_COMPRESS`` -- fp8 cold-page KV compression (default off)
* ``HOROVOD_PREFIX_CACHE`` -- radix prefix cache over the page pool
  (default off): a request whose prompt hits a cached prefix attaches
  the matched pages refcounted copy-on-write and prefills only the
  tail through the chunked path
* ``HOROVOD_SESSION_TTL_STEPS`` -- engine steps a session's warm KV
  context stays pinned without reuse (default 512)
* ``HOROVOD_TENANT_CLASSES`` -- per-tenant SLO classes,
  ``name:weight[:ttft_slo_s[:max_share]],...`` (default: single
  tenant)

The engine keeps two clocks: a VIRTUAL clock that fast-forwards through
idle gaps in the open-loop arrival schedule (TTFT and queueing are
measured against it, so latency percentiles are arrival-faithful), and
the real wall clock for throughput (tokens/s is never diluted by
fast-forwarded idle time).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import _env, _env_bool, _env_int
from ..timeline import spans as _spans
from .decode import (build_decode_step, build_verify_step, greedy_sample,
                     prefill_forward)
from .kvcache import (CacheConfig, PagedKVCache, PrefixCache,
                      cache_sharding)
from .scheduler import (ContinuousBatchScheduler, Request,
                        parse_tenant_classes)
from .spec import NgramDrafter


class _Stop:
    def __init__(self, error: Optional[BaseException] = None):
        self.error = error


class RequestPrefetcher:
    """Stage upcoming requests' prompts onto device ahead of admission.

    Same shape as ``data.DevicePrefetcher``: bounded queue, daemon
    producer, sentinel-carried errors, context-manager close.  Yields
    ``(request, device_prompt)`` in arrival order.
    """

    def __init__(self, requests: Sequence[Request], depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(list(requests),),
            name="serving-prefetch", daemon=True)
        self._thread.start()

    def _produce(self, requests):
        try:
            for req in requests:
                if self._closed.is_set():
                    return
                dev = jax.device_put(jnp.asarray(req.prompt, jnp.int32))
                while not self._closed.is_set():
                    try:
                        self._q.put((req, dev), timeout=0.1)
                        break
                    except queue.Full:
                        continue
            self._q.put(_Stop())
        except BaseException as e:  # surfaced in the consumer
            self._q.put(_Stop(e))

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if isinstance(item, _Stop):
            if item.error is not None:
                raise item.error
            raise StopIteration
        return item

    def close(self) -> None:
        self._closed.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


@dataclasses.dataclass
class ServingReport:
    """Aggregate result of one ``serve()`` run."""

    num_requests: int
    completed: int
    rejected: int
    prompt_tokens: int
    new_tokens: int
    wall_s: float
    decode_steps: int
    tokens_per_s: float
    ttft_p50_s: float
    ttft_p99_s: float
    token_latency_p50_s: float
    token_latency_p99_s: float
    mean_occupancy: float
    # Speculative decoding (zero when HOROVOD_SPEC_DECODE is off).
    spec_rounds: int = 0
    proposed_tokens: int = 0
    accepted_tokens: int = 0
    acceptance_rate: float = 0.0
    # Prefix cache (zero when HOROVOD_PREFIX_CACHE is off).
    prefix_queries: int = 0
    prefix_hits: int = 0
    prefix_hit_rate: float = 0.0
    prefill_tokens_cached: int = 0
    # Fraction of prompt tokens whose per-token prefill forward was
    # skipped outright (matched pages attached instead of computed) --
    # the "prefill FLOPs avoided" headline of BENCH_r17.
    prefill_flops_avoided: float = 0.0
    session_resumes: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _pct(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    return float(np.percentile(np.asarray(values, np.float64), q))


class ServingEngine:
    """Continuous-batching inference over one Llama-family model."""

    def __init__(self, config, params, *, mesh=None, slots: int = 0,
                 page_size: int = 0, max_len: int = 0, dtype=jnp.float32,
                 adapters=None, adapter_ids=None, lora_alpha: float = 16.0,
                 prefetch_depth: int = 0,
                 spec_decode: Optional[bool] = None, spec_k: int = 0,
                 drafter=None, prefill_chunk: int = -1,
                 kv_compress: Optional[bool] = None,
                 prefix_cache: Optional[bool] = None,
                 session_ttl_steps: int = 0, tenants=None):
        self.config = config
        self.params = params
        if mesh is None:
            from jax.sharding import Mesh
            mesh = Mesh(np.asarray(jax.devices()[:1]), ("tp",))
        self.mesh = mesh
        self.slots = slots or _env_int("SERVING_SLOTS", 8)
        self.page_size = page_size or _env_int("SERVING_PAGE_SIZE", 16)
        self.max_len = max_len or _env_int("SERVING_MAX_LEN",
                                           config.max_seq_len)
        self.prefetch_depth = prefetch_depth or _env_int(
            "SERVING_PREFETCH", 2)
        self.spec_decode = (_env_bool("SPEC_DECODE")
                            if spec_decode is None else bool(spec_decode))
        self.spec_k = spec_k or _env_int("SPEC_K", 4)
        self.prefill_chunk = (_env_int("PREFILL_CHUNK", 0)
                              if prefill_chunk < 0 else prefill_chunk)
        self.kv_compress = (_env_bool("KV_COMPRESS")
                            if kv_compress is None else bool(kv_compress))
        self.prefix_cache = (_env_bool("PREFIX_CACHE")
                             if prefix_cache is None
                             else bool(prefix_cache))
        self.session_ttl_steps = session_ttl_steps or _env_int(
            "SESSION_TTL_STEPS", 512)
        if tenants is None:
            spec = _env("TENANT_CLASSES")
            tenants = parse_tenant_classes(spec) if spec else None
        if self.spec_decode and self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.spec_k}")
        if adapters is not None and self.spec_decode:
            raise NotImplementedError(
                "speculative decoding with LoRA banks is not wired; "
                "run adapters through plain decode")
        if adapters is not None and self.kv_compress:
            raise NotImplementedError(
                "fp8 KV compression with LoRA banks is not wired")
        if adapters is not None and self.prefix_cache:
            raise NotImplementedError(
                "prefix cache with LoRA banks is not wired: cached K/V "
                "is keyed by tokens only, but LoRA'd wk/wv make K/V "
                "adapter-dependent")
        self.dtype = dtype
        self.adapters = adapters
        self.lora_alpha = lora_alpha
        self.cache_config = CacheConfig(
            num_layers=config.num_layers,
            num_kv_heads=config.num_kv_heads, head_dim=config.head_dim,
            slots=self.slots, page_size=self.page_size,
            max_len=self.max_len, dtype=str(jnp.dtype(dtype)),
            compress=self.kv_compress)
        self.cache = PagedKVCache(self.cache_config,
                                  cache_sharding(mesh))
        # Admission must price the widest step a slot can take: k drafts
        # + the target's bonus token under speculation, else 1.
        budget = self.spec_k + 1 if self.spec_decode else 1
        self.scheduler = ContinuousBatchScheduler(
            self.slots, self.cache, token_budget=budget,
            tenants=tenants)
        self._tenants = tenants
        # Radix prefix cache over the page pool: installed as the
        # cache's reclaim callback so page pressure demotes/evicts
        # cached prefixes instead of failing admission.
        self._prefix: Optional[PrefixCache] = None
        if self.prefix_cache:
            self._prefix = PrefixCache(
                self.cache, session_ttl_steps=self.session_ttl_steps)
        self.step = build_decode_step(
            config, mesh, slots=self.slots, page_size=self.page_size,
            pages_per_slot=self.cache_config.pages_per_slot, dtype=dtype,
            with_lora=adapters is not None, lora_alpha=lora_alpha,
            compress=self.kv_compress)
        self.verify_step = None
        if self.spec_decode:
            self.verify_step = build_verify_step(
                config, mesh, slots=self.slots, width=self.spec_k + 1,
                page_size=self.page_size,
                pages_per_slot=self.cache_config.pages_per_slot,
                dtype=dtype, compress=self.kv_compress)
            self.drafter = drafter if drafter is not None \
                else NgramDrafter()
        else:
            self.drafter = None

        def _prefill(p, toks, ad, aid):
            return prefill_forward(p, config, toks, dtype=dtype,
                                   adapters=ad, adapter_id=aid,
                                   lora_alpha=lora_alpha)

        def _prefill_chunk(p, toks, past):
            return prefill_forward(p, config, toks, dtype=dtype,
                                   past=past)

        self._prefill = jax.jit(_prefill)
        self._prefill_chunked = jax.jit(_prefill_chunk)
        # In-progress chunked prefills: slot -> dict(req, prompt, pos,
        # past).  Slots in here are state "prefill" and excluded from
        # the decode batch until their last chunk lands.
        self._chunking: Dict[int, Dict[str, Any]] = {}

    # -- one-request helpers ----------------------------------------------
    def _begin_prefill(self, st: Dict[str, Any], slot: int, req: Request,
                       dev, now) -> None:
        """Admit one request into its slot: radix-match the prompt
        against the prefix cache (attach matched pages, no compute),
        then prefill the remaining tail -- chunked when it is long.
        """
        matched, entries = 0, ()
        if self._prefix is not None:
            matched, entries = self._prefix.match(req.prompt)
            st["prefix_queries"] += 1
            if matched:
                st["prefix_hits"] += 1
                st["prefill_cached"] += matched
                self.cache.attach_pages(slot, entries, matched)
            st["prefill_computed"] += req.prompt_len - matched
            if req.session_id is not None and \
                    self._prefix.touch_session(req.session_id) and matched:
                st["session_resumes"] += 1
        if 0 < self.prefill_chunk < req.prompt_len - matched:
            # Long tail: fill in chunk-by-chunk, one chunk per loop
            # iteration, decode interleaved.  A matched prefix seeds
            # the running past from the cached pages.
            past = self.cache.gather_pages(entries) if matched else None
            self._chunking[slot] = {
                "req": req, "dev": dev, "pos": matched,
                "start": matched, "past": past}
        else:
            first = self._do_prefill(slot, req, dev, matched=matched,
                                     entries=entries)
            self._join_decode(st, slot, req, first, now)

    def _do_prefill(self, slot: int, req: Request, prompt_dev,
                    matched: int = 0, entries: Sequence = ()) -> int:
        with _spans.recorder().span("dispatch", name="prefill",
                                    leg="serving_prefill"):
            if matched:
                # Prefix hit: only the tail goes through the forward
                # pass, conditioned on the cached pages as past K/V --
                # the matched tokens' prefill FLOPs are avoided.
                past = self.cache.gather_pages(entries)
                logits, kl, vl = self._prefill_chunked(
                    self.params, prompt_dev[matched:][None], past)
                self.cache.write_prefill(slot, kl[:, 0, matched:],
                                         vl[:, 0, matched:],
                                         start=matched)
            else:
                aid = jnp.int32(req.adapter_id) \
                    if self.adapters is not None else None
                logits, kl, vl = self._prefill(
                    self.params, prompt_dev[None], self.adapters, aid)
                self.cache.write_prefill(slot, kl[:, 0], vl[:, 0])
            first = int(greedy_sample(logits[:, -1, :])[0])
        return first

    def _advance_chunks(self, st: Dict[str, Any], now) -> None:
        """Push each in-progress chunked prefill forward by ONE chunk.

        One chunk per slot per serve-loop iteration: a kilotoken
        admission is sliced into ``prefill_chunk``-token forwards
        interleaved with decode steps, so the live decode batch keeps
        emitting while the long prompt fills in (the TTFT-p99 gate).
        The final chunk's full-context K/V is scattered once -- chunked
        and whole-prompt prefill land the identical cache state.
        """
        for slot in list(self._chunking):
            c = self._chunking[slot]
            req: Request = c["req"]
            chunk = c["dev"][c["pos"]:c["pos"] + self.prefill_chunk]
            with _spans.recorder().span("dispatch", name="prefill_chunk",
                                        leg="serving_prefill_chunk"):
                logits, kl, vl = self._prefill_chunked(
                    self.params, chunk[None], c["past"])
            c["past"] = (kl, vl)
            c["pos"] += int(chunk.shape[0])
            if c["pos"] < req.prompt_len:
                continue
            del self._chunking[slot]
            start = int(c.get("start", 0))
            self.cache.write_prefill(slot, kl[:, 0, start:],
                                     vl[:, 0, start:], start=start)
            first = int(greedy_sample(logits[:, -1, :])[0])
            self._join_decode(st, slot, req, first, now)

    def _join_decode(self, st: Dict[str, Any], slot: int, req: Request,
                     first: int, now) -> None:
        """Prefill done (whole or final chunk): first token is sampled,
        the request enters the decode batch."""
        sched = self.scheduler
        req.tokens.append(first)
        sched.note_prefill(req, now())
        st["last_tokens"][slot] = first
        st["adapter_ids"][slot] = req.adapter_id
        if self._prefix is not None:
            # Register the prompt's full pages in the radix tree (tree
            # holds its own refs, so they outlive the slot) and pin the
            # session's path so multi-turn context stays warm.
            self._prefix.insert(req.prompt, slot)
            if req.session_id is not None:
                self._prefix.pin_session(req.session_id, req.prompt)
        if self.drafter is not None:
            self.drafter.on_admit(slot, req)
        if req.finished:
            self._release(st, slot, now)

    def _release(self, st: Dict[str, Any], slot: int, now) -> None:
        if self.drafter is not None:
            self.drafter.on_release(slot)
        st["completed"].append(self.scheduler.release(slot, now()))

    def _decode_slots(self) -> List[int]:
        """Slots actually in the decode batch: live requests minus
        still-chunking prefills and pages-in-flight handoffs (neither
        has resident context yet)."""
        return [s for s, r in self.scheduler.active.items()
                if r.state not in ("prefill", "handoff")]

    def _quarantine_logits(self, st: Dict[str, Any], slot: int,
                           req: Request) -> None:
        """A slot produced nonfinite logits: never stream a token
        sampled from a poisoned distribution.

        The slot's resident KV (or the dispatch that read it) is
        suspect, so rebuild the context from the request's own token
        history via :meth:`re_prefill` -- ``write_prefill`` re-derives
        the slot's length and page mapping from scratch, so the
        quarantine cannot leak pages -- and retry the same position on
        the next round.  The request keeps its slot and emitted prefix;
        only the round is lost.
        """
        from ..timeline import metrics as _metrics
        _metrics.registry().counter(
            "horovod_guard_serving_reprefills_total",
            "Decode rounds where a slot's nonfinite logits were "
            "quarantined by re-prefilling its context").inc()
        st["last_tokens"][slot] = self.re_prefill(slot, req)

    # -- one decode round (shared with serving.controlplane) ---------------
    def decode_once(self, st: Dict[str, Any], now) -> float:
        """One plain continuous-batching decode step over live slots.

        ``st`` is the mutable per-run state dict (``last_tokens``,
        ``adapter_ids``, ``completed``, ``occ_samples``,
        ``decode_steps``); the control plane's drain loop drives this
        same method so its gauges stay truthful.
        """
        sched = self.scheduler
        cache = self.cache
        slots = self._decode_slots()
        for slot in slots:
            length = int(cache.lengths[slot])
            cache.reserve(slot, length + 1, writable_from=length)
        active = np.zeros((self.slots,), bool)
        active[slots] = True
        args = [self.params, cache.k, cache.v,
                jnp.asarray(np.array(st["last_tokens"])),
                cache.lengths_device(), cache.table_device(),
                jnp.asarray(active)]
        if self.kv_compress:
            args += list(cache.compress_operands())
        if self.adapters is not None:
            args += [self.adapters,
                     jnp.asarray(np.array(st["adapter_ids"]))]
        t0 = time.monotonic()
        logits, cache.k, cache.v = self.step(*args)
        sampled = np.asarray(greedy_sample(logits))  # sync point
        # Per-slot SDC screen: one reduced scalar per row (sum propagates
        # any NaN/Inf in the vocab axis), fetched with the sample.
        finite = np.isfinite(np.asarray(jnp.sum(logits, axis=-1)))
        step_s = time.monotonic() - t0
        st["decode_steps"] += 1
        st["occ_samples"].append(sched.occupancy)
        for slot in slots:
            req = sched.active[slot]
            if not finite[slot]:
                self._quarantine_logits(st, slot, req)
                continue
            tok = int(sampled[slot])
            req.tokens.append(tok)
            cache.lengths[slot] += 1
            st["last_tokens"][slot] = tok
            sched.note_decode_token(req, step_s)
            if req.finished or int(cache.lengths[slot]) >= self.max_len:
                self._release(st, slot, now)
        return step_s

    def spec_round(self, st: Dict[str, Any], now) -> float:
        """One speculative round: draft k, verify k+1 wide, accept the
        longest agreeing prefix per slot.

        Greedy-exact by construction -- every emitted token is the
        TARGET model's argmax (column j's logits condition on the
        accepted prefix only), so the stream is bitwise identical to
        plain decode; the drafter only changes how many tokens one
        dispatch amortises.  Rejected draft K/V stays above the rolled-
        back length: masked garbage, the recycled-page contract.
        """
        sched = self.scheduler
        cache = self.cache
        k = self.spec_k
        width = k + 1
        slots = self._decode_slots()
        reqs = {s: sched.active[s] for s in slots}
        base = {s: int(cache.lengths[s]) for s in slots}
        for s in slots:
            # Room for this round's widest write, capped at the slot's
            # page allotment (columns past max_len scatter to scratch).
            cache.reserve(s, min(base[s] + width, self.max_len),
                          writable_from=base[s])
        drafts = self.drafter.propose(reqs, k,
                                      np.array(st["last_tokens"]))
        tokens_in = np.zeros((self.slots, width), np.int32)
        tokens_in[:, 0] = st["last_tokens"]
        tokens_in[:, 1:] = drafts
        active = np.zeros((self.slots,), bool)
        active[slots] = True
        args = [self.params, cache.k, cache.v, jnp.asarray(tokens_in),
                cache.lengths_device(), cache.table_device(),
                jnp.asarray(active)]
        if self.kv_compress:
            args += list(cache.compress_operands())
        t0 = time.monotonic()
        logits, cache.k, cache.v = self.verify_step(*args)
        sampled = np.asarray(greedy_sample(logits))  # [slots, width]
        # Per-slot SDC screen across every verify column: a poisoned
        # column anywhere in the window disqualifies the whole round for
        # that slot (the agreeing-prefix walk would condition on it).
        finite = np.isfinite(
            np.asarray(jnp.sum(logits, axis=(-2, -1))))
        step_s = time.monotonic() - t0
        st["decode_steps"] += 1
        st["spec_rounds"] = st.get("spec_rounds", 0) + 1
        st["occ_samples"].append(sched.occupancy)
        for s in slots:
            req = reqs[s]
            if not finite[s]:
                self._quarantine_logits(st, s, req)
                continue
            # Longest agreeing prefix: draft j survives iff every
            # earlier draft did AND it equals the target's argmax for
            # the position it sits at.
            m = 0
            while m < k and drafts[s, m] == sampled[s, m]:
                m += 1
            emit = min(m + 1,
                       req.max_new_tokens - len(req.tokens),
                       self.max_len - base[s])
            accepted = max(emit - 1, 0)
            st["proposed"] = st.get("proposed", 0) + k
            st["accepted"] = st.get("accepted", 0) + accepted
            sched.note_spec(k, accepted)
            for j in range(emit):
                req.tokens.append(int(sampled[s, j]))
                sched.note_decode_token(req, step_s / max(emit, 1))
            cache.lengths[s] = base[s] + emit
            st["last_tokens"][s] = req.tokens[-1]
            self.drafter.observe(s, req, accepted)
            if req.finished or int(cache.lengths[s]) >= self.max_len:
                self._release(st, s, now)
        return step_s

    # -- elastic resize hooks (driven by serving.controlplane) -------------
    def rebuild_mesh(self, mesh) -> None:
        """Swap the decode data plane onto a new tp mesh.

        The cache LAYOUT is mesh-size invariant by contract
        (``CacheConfig.layout``), so a resize is: fresh page pool with
        the new kv-head sharding, same scheduler (queue and in-flight
        request identity survive), and a rebuilt decode step.  The
        jitted ``_prefill`` is replicated math and carries over as-is --
        suspended requests are re-prefilled through it onto the new
        pool via :meth:`re_prefill`.
        """
        old_tp = int(self.mesh.devices.size)
        self.mesh = mesh
        self.cache = PagedKVCache(self.cache_config, cache_sharding(mesh))
        self.scheduler.cache = self.cache
        if self._prefix is not None:
            # Cached pages lived in the old pool: start a fresh tree
            # over the new one (suspended requests re-prefill anyway).
            self._prefix = PrefixCache(
                self.cache, session_ttl_steps=self.session_ttl_steps)
        self.step = build_decode_step(
            self.config, mesh, slots=self.slots, page_size=self.page_size,
            pages_per_slot=self.cache_config.pages_per_slot,
            dtype=self.dtype, with_lora=self.adapters is not None,
            lora_alpha=self.lora_alpha, compress=self.kv_compress)
        # The auditor's serving branch notes resize provenance so the
        # post-shrink gate can assert the exchange contract held.
        self.step._meta["resized_from"] = old_tp
        if self.verify_step is not None:
            self.verify_step = build_verify_step(
                self.config, mesh, slots=self.slots,
                width=self.spec_k + 1, page_size=self.page_size,
                pages_per_slot=self.cache_config.pages_per_slot,
                dtype=self.dtype, compress=self.kv_compress)
            self.verify_step._meta["resized_from"] = old_tp

    def re_prefill(self, slot: int, req: Request) -> int:
        """Rebuild a suspended request's KV on the CURRENT mesh from its
        prompt + emitted tokens; returns the next decode input token.

        All emitted tokens except the last are part of the restored
        context (their K/V must be resident); the last token is the one
        the next decode step consumes, exactly as if it had just been
        sampled on this mesh.
        """
        if not req.tokens:
            raise ValueError(f"request {req.rid} has no emitted tokens")
        full = np.concatenate([
            np.asarray(req.prompt, np.int32),
            np.asarray(req.tokens[:-1], np.int32)])
        with _spans.recorder().span("dispatch", name="reprefill",
                                    leg="serving_reprefill"):
            aid = jnp.int32(req.adapter_id) if self.adapters is not None \
                else None
            _, kl, vl = self._prefill(
                self.params, jnp.asarray(full)[None], self.adapters, aid)
            self.cache.write_prefill(slot, kl[:, 0], vl[:, 0])
        if self.drafter is not None:
            self.drafter.re_prefill(slot, req)
        return int(req.tokens[-1])

    # -- the serve loop ----------------------------------------------------
    def serve(self, requests: Sequence[Request]) -> ServingReport:
        """Run the open-loop request stream to completion."""
        sched = self.scheduler
        pending = sorted(requests, key=lambda r: r.arrival_s)
        rejected = 0
        admissible = []
        for req in pending:
            if req.prompt_len + req.max_new_tokens > self.max_len:
                rejected += 1
                sched._m_requests.labels(event="rejected").inc()
            else:
                admissible.append(req)

        start = time.monotonic()
        skip = 0.0

        def now() -> float:
            return time.monotonic() - start + skip

        st: Dict[str, Any] = {
            "completed": [], "occ_samples": [], "decode_steps": 0,
            "spec_rounds": 0, "proposed": 0, "accepted": 0,
            "prefix_queries": 0, "prefix_hits": 0,
            "prefill_cached": 0, "prefill_computed": 0,
            "session_resumes": 0,
            "last_tokens": np.zeros((self.slots,), np.int32),
            "adapter_ids": np.zeros((self.slots,), np.int32)}
        completed: List[Request] = st["completed"]
        prompts_dev: Dict[int, Any] = {}
        self._chunking.clear()

        with RequestPrefetcher(admissible, self.prefetch_depth) as feed:
            fetched = next(feed, None)

            while True:
                if self._prefix is not None:
                    # Advance the session-TTL clock every iteration
                    # (idle spins included) so pinned sessions always
                    # expire and page pressure can resolve.
                    self._prefix.tick()
                # Pull every request whose arrival time has passed.
                while fetched is not None and \
                        fetched[0].arrival_s <= now():
                    req, dev = fetched
                    prompts_dev[req.rid] = dev
                    sched.submit(req)
                    fetched = next(feed, None)
                if not sched.has_work():
                    if fetched is None:
                        break
                    # Idle: fast-forward the virtual clock to the next
                    # arrival instead of sleeping.
                    gap = fetched[0].arrival_s - now()
                    if gap > 0:
                        skip += gap
                    continue

                for slot, req in sched.admit(now()):
                    dev = prompts_dev.pop(req.rid)
                    self._begin_prefill(st, slot, req, dev, now)

                if self._chunking:
                    self._advance_chunks(st, now)
                if not self._decode_slots():
                    continue

                # One continuous-batching round over the decode batch:
                # a k-draft verify dispatch when speculating, else one
                # plain single-token step.
                if self.spec_decode:
                    self.spec_round(st, now)
                else:
                    self.decode_once(st, now)

        wall_s = max(time.monotonic() - start, 1e-9)
        new_tokens = sum(len(r.tokens) for r in completed)
        prompt_tokens = sum(r.prompt_len for r in completed)
        ttfts = [r.ttft_s for r in completed if r.ttft_s is not None]
        lats = [l for r in completed for l in r.token_latencies]
        proposed = int(st["proposed"])
        accepted = int(st["accepted"])
        pq, ph = int(st["prefix_queries"]), int(st["prefix_hits"])
        cached = int(st["prefill_cached"])
        computed = int(st["prefill_computed"])
        return ServingReport(
            num_requests=len(requests), completed=len(completed),
            rejected=rejected, prompt_tokens=prompt_tokens,
            new_tokens=new_tokens, wall_s=wall_s,
            decode_steps=int(st["decode_steps"]),
            tokens_per_s=new_tokens / wall_s,
            ttft_p50_s=_pct(ttfts, 50), ttft_p99_s=_pct(ttfts, 99),
            token_latency_p50_s=_pct(lats, 50),
            token_latency_p99_s=_pct(lats, 99),
            mean_occupancy=(float(np.mean(st["occ_samples"]))
                            if st["occ_samples"] else 0.0),
            spec_rounds=int(st["spec_rounds"]),
            proposed_tokens=proposed, accepted_tokens=accepted,
            acceptance_rate=(accepted / proposed if proposed else 0.0),
            prefix_queries=pq, prefix_hits=ph,
            prefix_hit_rate=(ph / pq if pq else 0.0),
            prefill_tokens_cached=cached,
            prefill_flops_avoided=(cached / (cached + computed)
                                   if cached + computed else 0.0),
            session_resumes=int(st["session_resumes"]))
