"""Serving engine front-end: prefetch -> prefill -> continuous decode.

One object owns the whole data plane: the paged KV cache, the
continuous-batching scheduler, the jitted prefill, and the
tensor-parallel decode step.  The request feed generalizes the
``data.DevicePrefetcher`` double-buffering idiom from training batches
to requests: a producer thread stages each upcoming prompt onto device
while the engine is still decoding, so admission never stalls on a
host-to-device copy.

Knobs (all overridable per-constructor-arg, documented in docs/api.md):

* ``HOROVOD_SERVING_SLOTS`` -- decode batch slots (default 8)
* ``HOROVOD_SERVING_PAGE_SIZE`` -- KV page length in tokens (default 16)
* ``HOROVOD_SERVING_MAX_LEN`` -- per-sequence cap (default: model max)
* ``HOROVOD_SERVING_PREFETCH`` -- request prefetch depth (default 2)

The engine keeps two clocks: a VIRTUAL clock that fast-forwards through
idle gaps in the open-loop arrival schedule (TTFT and queueing are
measured against it, so latency percentiles are arrival-faithful), and
the real wall clock for throughput (tokens/s is never diluted by
fast-forwarded idle time).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import _env_int
from ..timeline import spans as _spans
from .decode import build_decode_step, greedy_sample, prefill_forward
from .kvcache import CacheConfig, PagedKVCache, cache_sharding
from .scheduler import ContinuousBatchScheduler, Request


class _Stop:
    def __init__(self, error: Optional[BaseException] = None):
        self.error = error


class RequestPrefetcher:
    """Stage upcoming requests' prompts onto device ahead of admission.

    Same shape as ``data.DevicePrefetcher``: bounded queue, daemon
    producer, sentinel-carried errors, context-manager close.  Yields
    ``(request, device_prompt)`` in arrival order.
    """

    def __init__(self, requests: Sequence[Request], depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(list(requests),),
            name="serving-prefetch", daemon=True)
        self._thread.start()

    def _produce(self, requests):
        try:
            for req in requests:
                if self._closed.is_set():
                    return
                dev = jax.device_put(jnp.asarray(req.prompt, jnp.int32))
                while not self._closed.is_set():
                    try:
                        self._q.put((req, dev), timeout=0.1)
                        break
                    except queue.Full:
                        continue
            self._q.put(_Stop())
        except BaseException as e:  # surfaced in the consumer
            self._q.put(_Stop(e))

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if isinstance(item, _Stop):
            if item.error is not None:
                raise item.error
            raise StopIteration
        return item

    def close(self) -> None:
        self._closed.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


@dataclasses.dataclass
class ServingReport:
    """Aggregate result of one ``serve()`` run."""

    num_requests: int
    completed: int
    rejected: int
    prompt_tokens: int
    new_tokens: int
    wall_s: float
    decode_steps: int
    tokens_per_s: float
    ttft_p50_s: float
    ttft_p99_s: float
    token_latency_p50_s: float
    token_latency_p99_s: float
    mean_occupancy: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _pct(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    return float(np.percentile(np.asarray(values, np.float64), q))


class ServingEngine:
    """Continuous-batching inference over one Llama-family model."""

    def __init__(self, config, params, *, mesh=None, slots: int = 0,
                 page_size: int = 0, max_len: int = 0, dtype=jnp.float32,
                 adapters=None, adapter_ids=None, lora_alpha: float = 16.0,
                 prefetch_depth: int = 0):
        self.config = config
        self.params = params
        if mesh is None:
            from jax.sharding import Mesh
            mesh = Mesh(np.asarray(jax.devices()[:1]), ("tp",))
        self.mesh = mesh
        self.slots = slots or _env_int("SERVING_SLOTS", 8)
        self.page_size = page_size or _env_int("SERVING_PAGE_SIZE", 16)
        self.max_len = max_len or _env_int("SERVING_MAX_LEN",
                                           config.max_seq_len)
        self.prefetch_depth = prefetch_depth or _env_int(
            "SERVING_PREFETCH", 2)
        self.dtype = dtype
        self.adapters = adapters
        self.lora_alpha = lora_alpha
        self.cache_config = CacheConfig(
            num_layers=config.num_layers,
            num_kv_heads=config.num_kv_heads, head_dim=config.head_dim,
            slots=self.slots, page_size=self.page_size,
            max_len=self.max_len, dtype=str(jnp.dtype(dtype)))
        self.cache = PagedKVCache(self.cache_config,
                                  cache_sharding(mesh))
        self.scheduler = ContinuousBatchScheduler(self.slots, self.cache)
        self.step = build_decode_step(
            config, mesh, slots=self.slots, page_size=self.page_size,
            pages_per_slot=self.cache_config.pages_per_slot, dtype=dtype,
            with_lora=adapters is not None, lora_alpha=lora_alpha)

        def _prefill(p, toks, ad, aid):
            return prefill_forward(p, config, toks, dtype=dtype,
                                   adapters=ad, adapter_id=aid,
                                   lora_alpha=lora_alpha)

        self._prefill = jax.jit(_prefill)

    # -- one-request helpers ----------------------------------------------
    def _do_prefill(self, slot: int, req: Request, prompt_dev) -> int:
        with _spans.recorder().span("dispatch", name="prefill",
                                    leg="serving_prefill"):
            aid = jnp.int32(req.adapter_id) if self.adapters is not None \
                else None
            logits, kl, vl = self._prefill(self.params, prompt_dev[None],
                                           self.adapters, aid)
            self.cache.write_prefill(slot, kl[:, 0], vl[:, 0])
            first = int(greedy_sample(logits[:, -1, :])[0])
        return first

    # -- elastic resize hooks (driven by serving.controlplane) -------------
    def rebuild_mesh(self, mesh) -> None:
        """Swap the decode data plane onto a new tp mesh.

        The cache LAYOUT is mesh-size invariant by contract
        (``CacheConfig.layout``), so a resize is: fresh page pool with
        the new kv-head sharding, same scheduler (queue and in-flight
        request identity survive), and a rebuilt decode step.  The
        jitted ``_prefill`` is replicated math and carries over as-is --
        suspended requests are re-prefilled through it onto the new
        pool via :meth:`re_prefill`.
        """
        old_tp = int(self.mesh.devices.size)
        self.mesh = mesh
        self.cache = PagedKVCache(self.cache_config, cache_sharding(mesh))
        self.scheduler.cache = self.cache
        self.step = build_decode_step(
            self.config, mesh, slots=self.slots, page_size=self.page_size,
            pages_per_slot=self.cache_config.pages_per_slot,
            dtype=self.dtype, with_lora=self.adapters is not None,
            lora_alpha=self.lora_alpha)
        # The auditor's serving branch notes resize provenance so the
        # post-shrink gate can assert the exchange contract held.
        self.step._meta["resized_from"] = old_tp

    def re_prefill(self, slot: int, req: Request) -> int:
        """Rebuild a suspended request's KV on the CURRENT mesh from its
        prompt + emitted tokens; returns the next decode input token.

        All emitted tokens except the last are part of the restored
        context (their K/V must be resident); the last token is the one
        the next decode step consumes, exactly as if it had just been
        sampled on this mesh.
        """
        if not req.tokens:
            raise ValueError(f"request {req.rid} has no emitted tokens")
        full = np.concatenate([
            np.asarray(req.prompt, np.int32),
            np.asarray(req.tokens[:-1], np.int32)])
        with _spans.recorder().span("dispatch", name="reprefill",
                                    leg="serving_reprefill"):
            aid = jnp.int32(req.adapter_id) if self.adapters is not None \
                else None
            _, kl, vl = self._prefill(
                self.params, jnp.asarray(full)[None], self.adapters, aid)
            self.cache.write_prefill(slot, kl[:, 0], vl[:, 0])
        return int(req.tokens[-1])

    # -- the serve loop ----------------------------------------------------
    def serve(self, requests: Sequence[Request]) -> ServingReport:
        """Run the open-loop request stream to completion."""
        sched = self.scheduler
        cache = self.cache
        pending = sorted(requests, key=lambda r: r.arrival_s)
        rejected = 0
        admissible = []
        for req in pending:
            if req.prompt_len + req.max_new_tokens > self.max_len:
                rejected += 1
                sched._m_requests.labels(event="rejected").inc()
            else:
                admissible.append(req)

        start = time.monotonic()
        skip = 0.0

        def now() -> float:
            return time.monotonic() - start + skip

        completed: List[Request] = []
        occ_samples: List[float] = []
        decode_steps = 0
        last_tokens = np.zeros((self.slots,), np.int32)
        adapter_ids = np.zeros((self.slots,), np.int32)
        prompts_dev: Dict[int, Any] = {}

        with RequestPrefetcher(admissible, self.prefetch_depth) as feed:
            fetched = next(feed, None)

            while True:
                # Pull every request whose arrival time has passed.
                while fetched is not None and \
                        fetched[0].arrival_s <= now():
                    req, dev = fetched
                    prompts_dev[req.rid] = dev
                    sched.submit(req)
                    fetched = next(feed, None)
                if not sched.has_work():
                    if fetched is None:
                        break
                    # Idle: fast-forward the virtual clock to the next
                    # arrival instead of sleeping.
                    gap = fetched[0].arrival_s - now()
                    if gap > 0:
                        skip += gap
                    continue

                for slot, req in sched.admit(now()):
                    first = self._do_prefill(
                        slot, req, prompts_dev.pop(req.rid))
                    req.tokens.append(first)
                    sched.note_prefill(req, now())
                    last_tokens[slot] = first
                    adapter_ids[slot] = req.adapter_id
                    if req.finished:
                        completed.append(sched.release(slot, now()))

                if not sched.active:
                    continue

                # One continuous-batching decode step over live slots.
                for slot in sched.active:
                    cache.reserve(slot, int(cache.lengths[slot]) + 1)
                active = np.zeros((self.slots,), bool)
                for slot in sched.active:
                    active[slot] = True
                args = [self.params, cache.k, cache.v,
                        jnp.asarray(np.array(last_tokens)),
                        cache.lengths_device(), cache.table_device(),
                        jnp.asarray(active)]
                if self.adapters is not None:
                    args += [self.adapters,
                             jnp.asarray(np.array(adapter_ids))]
                t0 = time.monotonic()
                logits, cache.k, cache.v = self.step(*args)
                sampled = np.asarray(greedy_sample(logits))  # sync point
                step_s = time.monotonic() - t0
                decode_steps += 1
                occ_samples.append(sched.occupancy)

                for slot, req in list(sched.active.items()):
                    tok = int(sampled[slot])
                    req.tokens.append(tok)
                    cache.lengths[slot] += 1
                    last_tokens[slot] = tok
                    sched.note_decode_token(req, step_s)
                    if req.finished or \
                            int(cache.lengths[slot]) >= self.max_len:
                        completed.append(sched.release(slot, now()))

        wall_s = max(time.monotonic() - start, 1e-9)
        new_tokens = sum(len(r.tokens) for r in completed)
        prompt_tokens = sum(r.prompt_len for r in completed)
        ttfts = [r.ttft_s for r in completed if r.ttft_s is not None]
        lats = [l for r in completed for l in r.token_latencies]
        return ServingReport(
            num_requests=len(requests), completed=len(completed),
            rejected=rejected, prompt_tokens=prompt_tokens,
            new_tokens=new_tokens, wall_s=wall_s,
            decode_steps=decode_steps,
            tokens_per_s=new_tokens / wall_s,
            ttft_p50_s=_pct(ttfts, 50), ttft_p99_s=_pct(ttfts, 99),
            token_latency_p50_s=_pct(lats, 50),
            token_latency_p99_s=_pct(lats, 99),
            mean_occupancy=(float(np.mean(occ_samples))
                            if occ_samples else 0.0))
