"""Synthetic open-loop load generator.

Open-loop means arrivals are scheduled by a Poisson process BEFORE
service starts and do not slow down when the engine falls behind -- the
standard way to measure serving latency without coordinated omission
(a closed loop would stop submitting while the engine is busy, hiding
queueing delay from the TTFT distribution).

Everything is driven by one seeded ``numpy.random.RandomState``:
identical :class:`LoadSpec` -> identical request stream, byte for byte
(asserted in tests), so bench rounds are reproducible.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .scheduler import Request


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """Shape of the synthetic workload."""

    num_requests: int = 32
    rate_rps: float = 8.0                      # mean Poisson arrival rate
    prompt_lens: Tuple[int, ...] = (8, 16, 32)
    prompt_weights: Optional[Tuple[float, ...]] = None   # uniform if None
    output_lens: Tuple[int, ...] = (8, 16)
    output_weights: Optional[Tuple[float, ...]] = None
    vocab_size: int = 256
    num_adapters: int = 0                      # 0: base model only
    seed: int = 0

    def __post_init__(self):
        if self.num_requests < 1:
            raise ValueError("num_requests must be >= 1")
        if self.rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        for name, lens, weights in (
                ("prompt", self.prompt_lens, self.prompt_weights),
                ("output", self.output_lens, self.output_weights)):
            if not lens or any(x < 1 for x in lens):
                raise ValueError(f"{name}_lens must be positive: {lens}")
            if weights is not None and len(weights) != len(lens):
                raise ValueError(
                    f"{name}_weights length {len(weights)} != "
                    f"{len(lens)} choices")


def _norm(weights: Optional[Sequence[float]], n: int):
    if weights is None:
        return None
    w = np.asarray(weights, np.float64)
    return w / w.sum()


def long_prompt_spec(**overrides) -> LoadSpec:
    """The kilotoken-prompt mixture the chunked-prefill TTFT gate runs:
    512/2048/4096-token prompts weighted toward the long tail (the 4k
    bucket is what the BENCH_r15 TTFT p99 is measured on)."""
    base = dict(num_requests=16, rate_rps=2.0,
                prompt_lens=(512, 2048, 4096),
                prompt_weights=(0.5, 0.25, 0.25),
                output_lens=(8, 16), seed=0)
    base.update(overrides)
    return LoadSpec(**base)


def generate(spec: LoadSpec) -> List[Request]:
    """Materialize the request stream for ``spec`` (sorted by arrival)."""
    rng = np.random.RandomState(spec.seed)
    pw = _norm(spec.prompt_weights, len(spec.prompt_lens))
    ow = _norm(spec.output_weights, len(spec.output_lens))
    out: List[Request] = []
    t = 0.0
    for rid in range(spec.num_requests):
        # Poisson process: exponential inter-arrival gaps.
        t += float(rng.exponential(1.0 / spec.rate_rps))
        plen = int(rng.choice(spec.prompt_lens, p=pw))
        olen = int(rng.choice(spec.output_lens, p=ow))
        prompt = rng.randint(0, spec.vocab_size, size=plen).astype(np.int32)
        adapter = rid % spec.num_adapters if spec.num_adapters else 0
        out.append(Request(rid=rid, prompt=prompt, max_new_tokens=olen,
                           adapter_id=adapter, arrival_s=t))
    return out
