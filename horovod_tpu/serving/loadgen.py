"""Synthetic open-loop load generator.

Open-loop means arrivals are scheduled by a Poisson process BEFORE
service starts and do not slow down when the engine falls behind -- the
standard way to measure serving latency without coordinated omission
(a closed loop would stop submitting while the engine is busy, hiding
queueing delay from the TTFT distribution).

Everything is driven by one seeded ``numpy.random.RandomState``:
identical :class:`LoadSpec` -> identical request stream, byte for byte
(asserted in tests), so bench rounds are reproducible.  The PR 16
traffic shapes draw from the SAME stream in a fixed order, so turning
them off reproduces the pre-PR-16 streams exactly:

* prefix sharing -- ``prefix_share`` of requests prepend one of
  ``num_prefixes`` fixed shared prefixes (system prompts / RAG
  templates) to their unique tail, the workload the prefix cache's
  radix matching converts into avoided prefill FLOPs;
* multi-turn sessions -- ``session_share`` of requests open a session
  whose follow-up turns EXTEND the previous turn's prompt (same
  ``session_id``), exercising the warm-KV session path;
* tenant mix -- ``tenants`` assigns each request an SLO class name by
  weight, so the scheduler's weighted admission and the fairness gate
  have a mixed (or adversarial) population to schedule.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .scheduler import Request


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """Shape of the synthetic workload."""

    num_requests: int = 32
    rate_rps: float = 8.0                      # mean Poisson arrival rate
    prompt_lens: Tuple[int, ...] = (8, 16, 32)
    prompt_weights: Optional[Tuple[float, ...]] = None   # uniform if None
    output_lens: Tuple[int, ...] = (8, 16)
    output_weights: Optional[Tuple[float, ...]] = None
    vocab_size: int = 256
    num_adapters: int = 0                      # 0: base model only
    seed: int = 0
    # Prefix-shared traffic (0.0 disables, streams stay pre-PR-16
    # byte-identical): a shared request's prompt = one of
    # ``num_prefixes`` fixed prefixes (length from ``prefix_lens``)
    # ++ a unique tail of ``prompt_lens`` tokens.
    prefix_share: float = 0.0
    num_prefixes: int = 1
    prefix_lens: Tuple[int, ...] = (64,)
    # Multi-turn sessions: ``session_share`` of non-continuation
    # requests open a session; later requests continue the oldest open
    # session (prompt = previous turn's prompt ++ fresh delta) until it
    # reaches ``session_turns`` turns.
    session_share: float = 0.0
    session_turns: int = 1
    # Tenant mix: ``((name, arrival_weight), ...)``; empty = everyone
    # is the single implicit "default" tenant.
    tenants: Tuple[Tuple[str, float], ...] = ()
    # Fleet traffic shapes (PR 20; 0/empty disables, streams stay
    # byte-identical to the PR 16 generator):
    # * rate doubling -- arrivals at/after this offset come twice as
    #   fast (each post-boundary gap is halved AFTER the draw, so the
    #   underlying exponential stream is untouched), the step-function
    #   surge the fleet scaler must absorb;
    # * per-engine arrival skew -- each request draws an
    #   ``engine_hint`` from these weights (one per engine), modeling
    #   an external LB that sprays engines unevenly.  The router
    #   honors hints verbatim, so skew stresses spill/migration.
    rate_double_at_s: float = 0.0
    engine_skew: Tuple[float, ...] = ()

    def __post_init__(self):
        if self.num_requests < 1:
            raise ValueError("num_requests must be >= 1")
        if self.rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        for name, lens, weights in (
                ("prompt", self.prompt_lens, self.prompt_weights),
                ("output", self.output_lens, self.output_weights)):
            if not lens or any(x < 1 for x in lens):
                raise ValueError(f"{name}_lens must be positive: {lens}")
            if weights is not None and len(weights) != len(lens):
                raise ValueError(
                    f"{name}_weights length {len(weights)} != "
                    f"{len(lens)} choices")
        if not 0.0 <= self.prefix_share <= 1.0:
            raise ValueError(
                f"prefix_share must be in [0, 1]: {self.prefix_share}")
        if not 0.0 <= self.session_share <= 1.0:
            raise ValueError(
                f"session_share must be in [0, 1]: {self.session_share}")
        if self.prefix_share > 0 and (
                self.num_prefixes < 1 or not self.prefix_lens
                or any(x < 1 for x in self.prefix_lens)):
            raise ValueError(
                "prefix_share > 0 needs num_prefixes >= 1 and positive "
                "prefix_lens")
        if self.session_turns < 1:
            raise ValueError("session_turns must be >= 1")
        for t in self.tenants:
            if len(t) != 2 or not t[0] or float(t[1]) <= 0:
                raise ValueError(
                    f"tenants entries are (name, weight > 0): {t}")
        if self.rate_double_at_s < 0:
            raise ValueError(
                f"rate_double_at_s must be >= 0: {self.rate_double_at_s}")
        if self.engine_skew and any(
                float(w) < 0 for w in self.engine_skew):
            raise ValueError(
                f"engine_skew weights must be >= 0: {self.engine_skew}")
        if self.engine_skew and sum(self.engine_skew) <= 0:
            raise ValueError("engine_skew must have positive mass")


def _norm(weights: Optional[Sequence[float]], n: int):
    if weights is None:
        return None
    w = np.asarray(weights, np.float64)
    return w / w.sum()


def long_prompt_spec(**overrides) -> LoadSpec:
    """The kilotoken-prompt mixture the chunked-prefill TTFT gate runs:
    512/2048/4096-token prompts weighted toward the long tail (the 4k
    bucket is what the BENCH_r15 TTFT p99 is measured on)."""
    base = dict(num_requests=16, rate_rps=2.0,
                prompt_lens=(512, 2048, 4096),
                prompt_weights=(0.5, 0.25, 0.25),
                output_lens=(8, 16), seed=0)
    base.update(overrides)
    return LoadSpec(**base)


def prefix_spec(**overrides) -> LoadSpec:
    """The BENCH_r17 prefix-shared mixture: >= 50% of requests share
    one of a handful of fixed 64-token system prefixes, a quarter open
    two-turn sessions, and arrivals split across a gold/bronze tenant
    mix -- the workload where the radix prefix cache's avoided-prefill
    win is measurable."""
    base = dict(num_requests=40, rate_rps=30.0,
                prompt_lens=(8, 16), output_lens=(8, 16),
                prefix_share=0.6, num_prefixes=4, prefix_lens=(64,),
                session_share=0.25, session_turns=2,
                tenants=(("gold", 4.0), ("bronze", 1.0)), seed=0)
    base.update(overrides)
    return LoadSpec(**base)


def fleet_spec(**overrides) -> LoadSpec:
    """The BENCH_r20 fleet chaos mixture: prefix-shared traffic that
    DOUBLES its arrival rate partway through the run while an external
    LB skews arrivals 3:1 toward engine 0 -- the surge + imbalance the
    fleet router's spill path and the scaler's grow-under-traffic path
    must absorb together."""
    base = dict(num_requests=48, rate_rps=30.0,
                prompt_lens=(8, 16), output_lens=(8, 16),
                prefix_share=0.5, num_prefixes=4, prefix_lens=(64,),
                rate_double_at_s=0.8, engine_skew=(3.0, 1.0), seed=0)
    base.update(overrides)
    return LoadSpec(**base)


def generate(spec: LoadSpec) -> List[Request]:
    """Materialize the request stream for ``spec`` (sorted by arrival).

    Determinism contract: one RandomState, draws in a FIXED order per
    request, and each PR 16 feature draws only when enabled -- identical
    specs yield byte-identical streams, and all-defaults specs yield the
    exact pre-PR-16 streams.
    """
    rng = np.random.RandomState(spec.seed)
    pw = _norm(spec.prompt_weights, len(spec.prompt_lens))
    ow = _norm(spec.output_weights, len(spec.output_lens))
    tenant_names = [str(t[0]) for t in spec.tenants]
    tw = _norm([float(t[1]) for t in spec.tenants],
               len(spec.tenants)) if spec.tenants else None
    prefixes: List[np.ndarray] = []
    if spec.prefix_share > 0:
        for i in range(spec.num_prefixes):
            plen = int(spec.prefix_lens[i % len(spec.prefix_lens)])
            prefixes.append(rng.randint(
                0, spec.vocab_size, size=plen).astype(np.int32))
    sessions_on = spec.session_share > 0 and spec.session_turns > 1
    skw = _norm(spec.engine_skew, len(spec.engine_skew)) \
        if spec.engine_skew else None
    open_sessions: List[dict] = []   # FIFO of {sid, ctx, turns}
    next_sid = 0
    out: List[Request] = []
    t = 0.0
    for rid in range(spec.num_requests):
        # Poisson process: exponential inter-arrival gaps.  The rate
        # doubling halves the gap AFTER the draw, so the exponential
        # stream (and every later draw) is byte-identical to the
        # undoubled spec's.
        gap = float(rng.exponential(1.0 / spec.rate_rps))
        if spec.rate_double_at_s > 0 and t >= spec.rate_double_at_s:
            gap *= 0.5
        t += gap
        tenant = "default"
        if tenant_names:
            tenant = tenant_names[int(rng.choice(len(tenant_names),
                                                 p=tw))]
        cont = None
        if sessions_on and open_sessions and rng.rand() < 0.5:
            cont = open_sessions.pop(0)
        base = None
        if cont is None and prefixes and rng.rand() < spec.prefix_share:
            base = prefixes[int(rng.randint(len(prefixes)))]
        # Legacy draw order from here (gap happened above): prompt
        # length, output length, prompt tokens -- all-defaults specs
        # reproduce the pre-PR-16 streams byte for byte.
        plen = int(rng.choice(spec.prompt_lens, p=pw))
        olen = int(rng.choice(spec.output_lens, p=ow))
        tail = rng.randint(0, spec.vocab_size,
                           size=plen).astype(np.int32)
        sid: Optional[int] = None
        if cont is not None:
            # Session continuation: the previous turn's prompt plus a
            # fresh delta -- the stored context radix-matches whole.
            prompt = np.concatenate([cont["ctx"], tail])
            sid = cont["sid"]
            cont["turns"] += 1
            cont["ctx"] = prompt
            if cont["turns"] < spec.session_turns:
                open_sessions.append(cont)
        else:
            prompt = tail if base is None \
                else np.concatenate([base, tail])
            if sessions_on and rng.rand() < spec.session_share:
                sid = next_sid
                next_sid += 1
                open_sessions.append(
                    {"sid": sid, "ctx": prompt, "turns": 1})
        adapter = rid % spec.num_adapters if spec.num_adapters else 0
        # Engine skew draws LAST, so skew-free specs never touch the
        # stream (defaults byte-identical to the PR 16 generator).
        hint: Optional[int] = None
        if skw is not None:
            hint = int(rng.choice(len(skw), p=skw))
        out.append(Request(rid=rid, prompt=prompt, max_new_tokens=olen,
                           adapter_id=adapter, arrival_s=t,
                           tenant=tenant, session_id=sid,
                           engine_hint=hint))
    return out
