"""Hysteresis/cooldown scale policy for the serving control plane.

The policy is the *brain* of :mod:`horovod_tpu.serving.controlplane`: it
looks at one :class:`SLOSample` at a time (queue depth, windowed TTFT
p99, batch occupancy, fleet health) and emits a :class:`Decision`.  It
is deliberately free of any mesh/JAX machinery so it can be unit-tested
with plain numbers and swapped out (the control plane accepts any object
with ``decide``/``mark_applied``).

Decision precedence, highest first:

1. **Mandatory shrink** -- a rank in the serving mesh is dead (chaos
   ``kill@`` or a real preemption).  Bypasses hysteresis and cooldown:
   there is no point debouncing a dead device.
2. **Straggler eviction** -- the :class:`StragglerMonitor` eviction hook
   latched a rank whose lateness EWMA crossed the threshold.  Also
   bypasses cooldown; hysteresis lives in the EWMA itself.
3. **Voluntary grow** -- queue depth or TTFT p99 breached the SLO for
   ``hysteresis`` consecutive samples and the cooldown has elapsed.
4. **Voluntary shrink** -- occupancy stayed under the low-water mark
   with an empty queue for ``hysteresis`` consecutive samples, cooldown
   elapsed.

Targets only ever move along the *valid tp ladder*: sizes that divide
``num_heads``, ``num_kv_heads`` and ``ffn_hidden`` (the
``build_decode_step`` contract), capped by the surviving healthy device
count and the ``HOROVOD_CTL_MIN_TP``/``HOROVOD_CTL_MAX_TP`` envelope.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from ..core.config import _env_float, _env_int

__all__ = [
    "PolicyConfig",
    "SLOSample",
    "Decision",
    "ScalePolicy",
    "valid_tp_sizes",
    "FleetPolicyConfig",
    "FleetSample",
    "FleetPolicy",
]


def valid_tp_sizes(config, max_devices: int) -> list:
    """Power-of-two tp sizes <= ``max_devices`` accepted by
    ``build_decode_step`` for ``config`` (head/kv-head/ffn divisibility)."""
    sizes = []
    s = 1
    while s <= max_devices:
        if (config.num_heads % s == 0 and config.num_kv_heads % s == 0
                and config.ffn_hidden % s == 0):
            sizes.append(s)
        s *= 2
    return sizes


@dataclasses.dataclass(frozen=True)
class PolicyConfig:
    """Knobs for :class:`ScalePolicy`; see ``from_env`` for the
    ``HOROVOD_CTL_*`` spellings documented in docs/api.md."""

    interval_s: float = 0.25       # controller sampling cadence
    ttft_slo_s: float = 0.5        # TTFT p99 objective over the window
    queue_high: int = 8            # queue depth that counts as overload
    occupancy_low: float = 0.25    # occupancy under this + empty queue =
                                   # underload
    hysteresis: int = 2            # consecutive breach samples required
    cooldown_s: float = 1.0        # min seconds between voluntary moves
    evict_lateness_s: float = 0.25  # straggler EWMA eviction threshold
    drain_steps: int = 16          # decode-step budget for graceful drain
    min_tp: int = 1
    max_tp: int = 8

    @classmethod
    def from_env(cls) -> "PolicyConfig":
        d = cls()
        return cls(
            interval_s=_env_float("CTL_INTERVAL_S", d.interval_s),
            ttft_slo_s=_env_float("CTL_TTFT_SLO_S", d.ttft_slo_s),
            queue_high=_env_int("CTL_QUEUE_HIGH", d.queue_high),
            occupancy_low=_env_float("CTL_OCC_LOW", d.occupancy_low),
            hysteresis=_env_int("CTL_HYSTERESIS", d.hysteresis),
            cooldown_s=_env_float("CTL_COOLDOWN_S", d.cooldown_s),
            evict_lateness_s=_env_float("CTL_EVICT_LATENESS_S",
                                        d.evict_lateness_s),
            drain_steps=_env_int("CTL_DRAIN_STEPS", d.drain_steps),
            min_tp=_env_int("CTL_MIN_TP", d.min_tp),
            max_tp=_env_int("CTL_MAX_TP", d.max_tp),
        )


@dataclasses.dataclass(frozen=True)
class SLOSample:
    """One controller observation window, all host-side numbers."""

    now_s: float
    queue_depth: int
    ttft_p99_s: Optional[float]    # None when the window saw no TTFTs
    occupancy: float               # mean active-slot fraction, 0..1
    mesh_size: int
    mesh_ranks: Tuple[int, ...]    # global device ids serving right now
    healthy: Tuple[int, ...]       # global device ids still usable
    dead_ranks: Tuple[int, ...] = ()
    evict_candidate: Optional[Tuple[int, float]] = None  # (rank, lateness)
    # Radix prefix-cache hit rate 0..1 (None when the cache is off):
    # a policy can weigh a scale-down differently when most prefill is
    # being absorbed by cached pages.
    prefix_hit_rate: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class Decision:
    action: str                    # "hold" | "grow" | "shrink" | "evict"
    reason: str
    target_size: Optional[int] = None
    evict_rank: Optional[int] = None

    @property
    def is_hold(self) -> bool:
        return self.action == "hold"


class ScalePolicy:
    """Hysteresis + cooldown debouncing around the valid-tp ladder."""

    def __init__(self, config: PolicyConfig, valid_sizes: Sequence[int]):
        self.config = config
        self.valid_sizes = sorted(
            s for s in valid_sizes
            if config.min_tp <= s <= config.max_tp)
        if not self.valid_sizes:
            raise ValueError(
                f"no valid tp sizes in [{config.min_tp}, {config.max_tp}] "
                f"from {sorted(valid_sizes)}")
        self._breach_high = 0
        self._breach_low = 0
        self._last_action_s = float("-inf")
        self._evicted = set()

    # -- ladder helpers ---------------------------------------------------
    def _fit(self, limit: int) -> Optional[int]:
        """Largest valid size <= ``limit``, or None."""
        ok = [s for s in self.valid_sizes if s <= limit]
        return ok[-1] if ok else None

    def _next_up(self, size: int, limit: int) -> Optional[int]:
        ok = [s for s in self.valid_sizes if size < s <= limit]
        return ok[0] if ok else None

    def _next_down(self, size: int) -> Optional[int]:
        ok = [s for s in self.valid_sizes if s < size]
        return ok[-1] if ok else None

    # -- the decision function --------------------------------------------
    def decide(self, s: SLOSample) -> Decision:
        cfg = self.config

        # 1. Dead rank in the serving mesh: mandatory resize onto the
        # survivors (possibly same size, if spare healthy devices exist).
        dead_in_mesh = [r for r in s.dead_ranks if r in s.mesh_ranks]
        if dead_in_mesh:
            target = self._fit(len(s.healthy))
            if target is None:
                return Decision("hold", "rank-dead:no-viable-size")
            return Decision("shrink", "rank-dead", target_size=target)

        # 2. Straggler eviction latched by the monitor hook.
        if s.evict_candidate is not None:
            rank, lateness = s.evict_candidate
            if rank in s.mesh_ranks and rank not in self._evicted:
                target = self._fit(len(s.healthy) - 1)
                if target is not None:
                    self._evicted.add(rank)
                    return Decision(
                        "evict",
                        f"straggler-lateness:{lateness:.3f}s",
                        target_size=target, evict_rank=rank)

        # 3/4. Voluntary moves: hysteresis counters + cooldown.
        overload = (s.queue_depth >= cfg.queue_high
                    or (s.ttft_p99_s is not None
                        and s.ttft_p99_s > cfg.ttft_slo_s))
        underload = (s.occupancy <= cfg.occupancy_low
                     and s.queue_depth == 0)
        self._breach_high = self._breach_high + 1 if overload else 0
        self._breach_low = self._breach_low + 1 if underload else 0

        cooled = s.now_s - self._last_action_s >= cfg.cooldown_s
        if self._breach_high >= cfg.hysteresis and cooled:
            target = self._next_up(s.mesh_size, len(s.healthy))
            if target is not None:
                return Decision("grow", "slo-breach", target_size=target)
        if self._breach_low >= cfg.hysteresis and cooled:
            target = self._next_down(s.mesh_size)
            if target is not None:
                return Decision("shrink", "underload", target_size=target)
        return Decision("hold", "steady")

    def mark_applied(self, decision: Decision, now_s: float) -> None:
        """Controller feedback: a decision was executed -- restart the
        cooldown clock and clear the breach counters."""
        if decision.is_hold:
            return
        self._last_action_s = now_s
        self._breach_high = 0
        self._breach_low = 0


# -- fleet-level policy (disaggregated serving, PR 20) ---------------------

@dataclasses.dataclass(frozen=True)
class FleetPolicyConfig:
    """Knobs for :class:`FleetPolicy` -- the fleet-level analogue of
    :class:`PolicyConfig`.  Where the per-engine policy moves ONE
    engine along the tp ladder, the fleet policy adds WHOLE decode
    engines (grow-by-adding-capacity); it never shrinks, because
    retiring an engine under live sessions is a migration problem the
    operator triggers explicitly."""

    interval_s: float = 0.25       # fleet controller cadence
    queue_high: int = 8            # fleet-wide queued requests = overload
    ttft_slo_s: float = 0.5        # fleet TTFT p99 objective
    hysteresis: int = 2            # consecutive breach samples required
    cooldown_s: float = 1.0        # min seconds between engine adds
    max_engines: int = 4           # hard capacity ceiling

    @classmethod
    def from_env(cls) -> "FleetPolicyConfig":
        d = cls()
        return cls(
            interval_s=_env_float("FLEET_INTERVAL_S", d.interval_s),
            queue_high=_env_int("FLEET_QUEUE_HIGH", d.queue_high),
            ttft_slo_s=_env_float("FLEET_TTFT_SLO_S", d.ttft_slo_s),
            hysteresis=_env_int("FLEET_HYSTERESIS", d.hysteresis),
            cooldown_s=_env_float("FLEET_COOLDOWN_S", d.cooldown_s),
            max_engines=_env_int("FLEET_MAX_ENGINES", d.max_engines),
        )


@dataclasses.dataclass(frozen=True)
class FleetSample:
    """One fleet-controller observation: sums/percentiles across every
    registered decode engine."""

    now_s: float
    queue_depth: int               # total queued across engines
    ttft_p99_s: Optional[float]    # fleet-wide windowed p99 (None = none)
    occupancy: float               # mean occupancy across engines
    engines: int                   # decode engines currently registered


class FleetPolicy:
    """Add-only engine scaling with the same hysteresis + cooldown
    debouncing :class:`ScalePolicy` uses -- a transient arrival burst
    must not commission hardware."""

    def __init__(self, config: Optional[FleetPolicyConfig] = None):
        self.config = config or FleetPolicyConfig.from_env()
        self._breach = 0
        self._last_action_s = float("-inf")

    def decide(self, s: FleetSample) -> Decision:
        cfg = self.config
        overload = (s.queue_depth >= cfg.queue_high
                    or (s.ttft_p99_s is not None
                        and s.ttft_p99_s > cfg.ttft_slo_s))
        self._breach = self._breach + 1 if overload else 0
        cooled = s.now_s - self._last_action_s >= cfg.cooldown_s
        if (self._breach >= cfg.hysteresis and cooled
                and s.engines < cfg.max_engines):
            return Decision("add-engine", "fleet-slo-breach",
                            target_size=s.engines + 1)
        return Decision("hold", "steady")

    def mark_applied(self, decision: Decision, now_s: float) -> None:
        if decision.is_hold:
            return
        self._last_action_s = now_s
        self._breach = 0
