"""Sharded paged KV cache for the serving data plane.

Physical layout is a fixed page pool per layer::

    k, v: [num_layers, num_pages, page_size, num_kv_heads, head_dim]

sharded over the ``tp`` mesh axis on the kv-head dim (the same split the
tensor-parallel decode step gives the attention projections, so a rank's
cache shard pairs exactly with its ``wk``/``wv`` kernel shards and no
cross-rank traffic ever touches the cache).  The LOGICAL view -- which
pages belong to which batch slot, and how many tokens are live -- is
host-side metadata: an int32 ``page_table[slots, pages_per_slot]`` plus a
``lengths[slots]`` vector, shipped into the compiled step as plain
replicated operands.  Correctness never depends on page contents being
zeroed: every read masks positions ``>= lengths`` through
:func:`horovod_tpu.ops.attention.decode_attention`, so a recycled page's
stale keys are unreachable by construction (the eviction/reuse test
asserts this bit-for-bit).

Pages are allocated lazily from a free list as a slot's sequence grows
and returned wholesale on eviction -- continuous batching recycles slots
mid-flight, so the pool, not the slot count, bounds resident KV bytes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Static shape of the pool (identical on every rank and mesh size)."""

    num_layers: int
    num_kv_heads: int
    head_dim: int
    slots: int
    page_size: int
    max_len: int
    dtype: str = "float32"

    def __post_init__(self):
        if self.max_len % self.page_size:
            raise ValueError(
                f"max_len {self.max_len} not a multiple of page_size "
                f"{self.page_size}")

    @property
    def pages_per_slot(self) -> int:
        return self.max_len // self.page_size

    @property
    def num_pages(self) -> int:
        return self.slots * self.pages_per_slot

    @property
    def scratch_page(self) -> int:
        """Index of the write sink: the decode step writes EVERY slot's
        K/V unconditionally (fixed-shape batch), so idle slots are
        redirected to this extra page past the allocatable pool instead
        of clobbering page 0."""
        return self.num_pages

    def layout(self) -> dict:
        """GLOBAL layout descriptor.  Mesh-size invariant by contract:
        the pool shape, page table geometry and dtype never depend on
        how many ranks the kv-head dim is split over (asserted by
        tests/test_serving.py across 1- and 8-device meshes)."""
        return {
            "kv_shape": [self.num_layers, self.num_pages + 1,
                         self.page_size, self.num_kv_heads, self.head_dim],
            "page_table_shape": [self.slots, self.pages_per_slot],
            "page_size": self.page_size,
            "pages_per_slot": self.pages_per_slot,
            "num_pages": self.num_pages,
            "scratch_page": self.scratch_page,
            "dtype": str(jnp.dtype(self.dtype)),
        }


class PagedKVCache:
    """Device page pool + host page table / free list for one model."""

    def __init__(self, config: CacheConfig, sharding=None):
        self.config = config
        c = config
        # +1: trailing scratch page, the write sink for idle slots.
        shape = (c.num_layers, c.num_pages + 1, c.page_size,
                 c.num_kv_heads, c.head_dim)
        k = jnp.zeros(shape, jnp.dtype(c.dtype))
        v = jnp.zeros(shape, jnp.dtype(c.dtype))
        if sharding is not None:
            k = jax.device_put(k, sharding)
            v = jax.device_put(v, sharding)
        self.sharding = sharding
        self.k = k
        self.v = v
        # Host-side logical view.  Unallocated table entries point at
        # page 0 -- harmless, reads beyond ``lengths`` are masked.
        self.page_table = np.zeros((c.slots, c.pages_per_slot), np.int32)
        self.lengths = np.zeros((c.slots,), np.int32)
        self._allocated = np.zeros((c.slots,), np.int32)  # pages per slot
        self._free = list(range(c.num_pages - 1, -1, -1))  # pop() -> 0, 1...

    # -- page accounting ---------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        """Pages currently held by slots (free_pages + allocated_pages
        == num_pages is the pool invariant the drain tests assert)."""
        return int(self._allocated.sum())

    def can_admit(self, length: int) -> bool:
        """Whether a sequence of ``length`` tokens fits the pool now."""
        need = -(-max(int(length), 1) // self.config.page_size)
        return need <= len(self._free)

    def reserve(self, slot: int, length: int) -> None:
        """Ensure slot ``slot`` has pages for ``length`` tokens."""
        c = self.config
        if length > c.max_len:
            raise ValueError(f"length {length} exceeds max_len {c.max_len}")
        need = -(-int(length) // c.page_size)
        have = int(self._allocated[slot])
        if need > have:
            if need - have > len(self._free):
                raise RuntimeError(
                    f"KV page pool exhausted: slot {slot} needs "
                    f"{need - have} page(s), {len(self._free)} free")
            for i in range(have, need):
                self.page_table[slot, i] = self._free.pop()
            self._allocated[slot] = need

    def free_slot(self, slot: int) -> None:
        """Return the slot's pages to the pool and mark it idle.  Page
        CONTENTS are deliberately left in place: the masking contract,
        not zeroing, is what guarantees no stale attention mass."""
        n = int(self._allocated[slot])
        for i in range(n - 1, -1, -1):
            self._free.append(int(self.page_table[slot, i]))
        self._allocated[slot] = 0
        self.lengths[slot] = 0

    def release_all(self) -> int:
        """Free every slot and return how many pages that recovered.

        The drain path frees each suspended slot individually, so a
        healthy shrink sees ``release_all() == 0`` afterwards -- the
        control-plane tests use that as the exact-release check (a
        non-zero return means a slot leaked its pages past the drain).
        """
        freed = 0
        for slot in range(self.config.slots):
            n = int(self._allocated[slot])
            if n:
                freed += n
                self.free_slot(slot)
        return freed

    # -- device writes -----------------------------------------------------
    def write_prefill(self, slot: int, k_layers, v_layers) -> None:
        """Scatter a prefilled prompt's K/V into the slot's pages.

        ``k_layers``/``v_layers``: ``[num_layers, t, num_kv_heads,
        head_dim]`` (post-RoPE, as the decode step expects).  Reserves
        pages for ``t`` tokens and sets ``lengths[slot] = t``.
        """
        c = self.config
        t = int(k_layers.shape[1])
        self.reserve(slot, t)
        pos = np.arange(t)
        pages = jnp.asarray(self.page_table[slot][pos // c.page_size])
        offs = jnp.asarray(pos % c.page_size)
        dt = jnp.dtype(c.dtype)
        # One scatter per pool: [L, t, H, D] lands at (page, off) pairs.
        self.k = self.k.at[:, pages, offs].set(k_layers.astype(dt))
        self.v = self.v.at[:, pages, offs].set(v_layers.astype(dt))
        self.lengths[slot] = t

    def grow(self, slot: int) -> None:
        """Account one decoded token (the decode step already wrote its
        K/V in-step); reserves the next page at a boundary crossing."""
        new_len = int(self.lengths[slot]) + 1
        self.reserve(slot, new_len)
        self.lengths[slot] = new_len

    # -- step operands -----------------------------------------------------
    def table_device(self) -> jnp.ndarray:
        # np.array copy matters: jnp.asarray of host numpy is zero-copy
        # on CPU, so the device operand would ALIAS the mutable host
        # table and later host updates would race the dispatched step.
        return jnp.asarray(np.array(self.page_table))

    def lengths_device(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.lengths))

    def layout(self) -> dict:
        return self.config.layout()


def cache_sharding(mesh, tp_axis: str = "tp"):
    """NamedSharding splitting the kv-head dim over ``tp`` (dims:
    layers, pages, page_size, kv_heads, head_dim)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if mesh is None:
        return None
    return NamedSharding(mesh, P(None, None, None, tp_axis, None))
