"""Sharded paged KV cache for the serving data plane.

Physical layout is a fixed page pool per layer::

    k, v: [num_layers, num_pages, page_size, num_kv_heads, head_dim]

sharded over the ``tp`` mesh axis on the kv-head dim (the same split the
tensor-parallel decode step gives the attention projections, so a rank's
cache shard pairs exactly with its ``wk``/``wv`` kernel shards and no
cross-rank traffic ever touches the cache).  The LOGICAL view -- which
pages belong to which batch slot, and how many tokens are live -- is
host-side metadata: an int32 ``page_table[slots, pages_per_slot]`` plus a
``lengths[slots]`` vector, shipped into the compiled step as plain
replicated operands.  Correctness never depends on page contents being
zeroed: every read masks positions ``>= lengths`` through
:func:`horovod_tpu.ops.attention.decode_attention`, so a recycled page's
stale keys are unreachable by construction (the eviction/reuse test
asserts this bit-for-bit).

Pages are allocated lazily from a free list as a slot's sequence grows
and returned wholesale on eviction -- continuous batching recycles slots
mid-flight, so the pool, not the slot count, bounds resident KV bytes.

fp8 cold-page compression (``CacheConfig(compress=True)``): pages that
sit ``hot_pages`` full pages behind a slot's write head are *cold* --
decode only reads them, never writes them again while the slot lives.
A cold page can be migrated into a parallel e4m3 pool through the PR 5
fp8 codec (:func:`~horovod_tpu.collectives.compression.fp8_quantize`,
one max-abs scale per token-layer row so an all-zero row roundtrips to
exact zeros), after which its f32 page returns to the free list.  The
decode/verify steps blend the two pools on gather (``comp_mask`` picks
the dequantised e4m3 page), so compression is invisible to the masking
contract: a recycled compressed page's stale bytes are unreachable for
exactly the reason a recycled f32 page's are.  Admission is therefore
page-gated on COMPRESSED size: ``can_admit``/``reserve`` count cold
pages at their e4m3 cost (compressing on demand to reclaim f32 pages),
so the same physical pool admits roughly 4x the cold-token residency.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..collectives.compression import fp8_quantize


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Static shape of the pool (identical on every rank and mesh size)."""

    num_layers: int
    num_kv_heads: int
    head_dim: int
    slots: int
    page_size: int
    max_len: int
    dtype: str = "float32"
    compress: bool = False         # fp8 cold-page compression on/off
    hot_pages: int = 1             # full pages behind the head kept f32

    def __post_init__(self):
        if self.max_len % self.page_size:
            raise ValueError(
                f"max_len {self.max_len} not a multiple of page_size "
                f"{self.page_size}")
        if self.hot_pages < 0:
            raise ValueError(f"hot_pages must be >= 0: {self.hot_pages}")

    @property
    def pages_per_slot(self) -> int:
        return self.max_len // self.page_size

    @property
    def num_pages(self) -> int:
        return self.slots * self.pages_per_slot

    @property
    def scratch_page(self) -> int:
        """Index of the write sink: the decode step writes EVERY slot's
        K/V unconditionally (fixed-shape batch), so idle slots are
        redirected to this extra page past the allocatable pool instead
        of clobbering page 0."""
        return self.num_pages

    def layout(self) -> dict:
        """GLOBAL layout descriptor.  Mesh-size invariant by contract:
        the pool shape, page table geometry and dtype never depend on
        how many ranks the kv-head dim is split over (asserted by
        tests/test_serving.py across 1- and 8-device meshes)."""
        return {
            "kv_shape": [self.num_layers, self.num_pages + 1,
                         self.page_size, self.num_kv_heads, self.head_dim],
            "page_table_shape": [self.slots, self.pages_per_slot],
            "page_size": self.page_size,
            "pages_per_slot": self.pages_per_slot,
            "num_pages": self.num_pages,
            "scratch_page": self.scratch_page,
            "dtype": str(jnp.dtype(self.dtype)),
        }


class PagedKVCache:
    """Device page pool + host page table / free list for one model."""

    def __init__(self, config: CacheConfig, sharding=None):
        self.config = config
        c = config
        # +1: trailing scratch page, the write sink for idle slots.
        shape = (c.num_layers, c.num_pages + 1, c.page_size,
                 c.num_kv_heads, c.head_dim)
        k = jnp.zeros(shape, jnp.dtype(c.dtype))
        v = jnp.zeros(shape, jnp.dtype(c.dtype))
        if sharding is not None:
            k = jax.device_put(k, sharding)
            v = jax.device_put(v, sharding)
        self.sharding = sharding
        self.k = k
        self.v = v
        # Host-side logical view.  Unallocated table entries point at
        # page 0 -- harmless, reads beyond ``lengths`` are masked.
        self.page_table = np.zeros((c.slots, c.pages_per_slot), np.int32)
        self.lengths = np.zeros((c.slots,), np.int32)
        self._allocated = np.zeros((c.slots,), np.int32)  # pages per slot
        self._free = list(range(c.num_pages - 1, -1, -1))  # pop() -> 0, 1...
        # fp8 cold-page pool: a parallel e4m3 page space plus one max-abs
        # scale per (layer, page, offset) row, blended in on gather by the
        # decode/verify steps wherever ``comp_mask`` is set.
        self.compress = bool(c.compress)
        if self.compress:
            self.kq = jnp.zeros(shape, jnp.float8_e4m3fn)
            self.vq = jnp.zeros(shape, jnp.float8_e4m3fn)
            if sharding is not None:
                self.kq = jax.device_put(self.kq, sharding)
                self.vq = jax.device_put(self.vq, sharding)
            sshape = (c.num_layers, c.num_pages + 1, c.page_size)
            self.kscale = jnp.ones(sshape, jnp.float32)
            self.vscale = jnp.ones(sshape, jnp.float32)
            self.cpage_table = np.zeros((c.slots, c.pages_per_slot),
                                        np.int32)
            self.comp_mask = np.zeros((c.slots, c.pages_per_slot), bool)
            self._cfree = list(range(c.num_pages - 1, -1, -1))
            self._cheld = np.zeros((c.slots,), np.int32)

    # -- page accounting ---------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        """f32 pages currently held by slots (free_pages +
        allocated_pages == num_pages is the pool invariant the drain
        tests assert; compressed pages live in the e4m3 pool and are
        accounted by :attr:`compressed_pages`)."""
        total = int(self._allocated.sum())
        if self.compress:
            total -= int(self._cheld.sum())
        return total

    @property
    def compressed_pages(self) -> int:
        return int(self._cheld.sum()) if self.compress else 0

    @property
    def resident_bytes(self) -> int:
        """Logical KV residency at COMPRESSED accounting: f32 pages at
        full price, cold e4m3 pages at one byte per element plus the
        per-row f32 scale (the number ``can_admit`` effectively budgets
        against)."""
        c = self.config
        row = c.num_kv_heads * c.head_dim
        page_f32 = c.num_layers * c.page_size * row * 2 \
            * jnp.dtype(c.dtype).itemsize
        page_fp8 = c.num_layers * c.page_size * (row + 4) * 2
        return (self.allocated_pages * page_f32
                + self.compressed_pages * page_fp8)

    def _cold_candidates(self, exclude: Optional[int] = None
                         ) -> List[int]:
        """Slots ordered by how many not-yet-compressed cold pages they
        hold (descending) -- the reclaim sweep order."""
        c = self.config
        out = []
        for slot in range(c.slots):
            if slot == exclude:
                continue
            n = self._cold_count(slot)
            if n > 0:
                out.append((n, slot))
        return [slot for _, slot in sorted(out, reverse=True)]

    def _cold_count(self, slot: int) -> int:
        """Cold pages of ``slot`` still resident in f32: full pages at
        least ``hot_pages`` behind the write head, minus the compressed
        prefix.  Pages at or past ``lengths`` are NEVER cold -- the
        decode/verify steps may still write them (speculative rejects
        roll ``lengths`` back below already-written positions)."""
        c = self.config
        full = int(self.lengths[slot]) // c.page_size
        return max(0, full - c.hot_pages - int(self._cheld[slot]))

    def can_admit(self, length: int) -> bool:
        """Whether a sequence of ``length`` tokens fits the pool now.

        With compression the gate prices cold pages at their compressed
        size: f32 pages reclaimable by a cold sweep (bounded by e4m3
        pool headroom) count as free."""
        need = -(-max(int(length), 1) // self.config.page_size)
        avail = len(self._free)
        if self.compress:
            cold = sum(self._cold_count(s)
                       for s in range(self.config.slots))
            avail += min(cold, len(self._cfree))
        return need <= avail

    def reserve(self, slot: int, length: int) -> None:
        """Ensure slot ``slot`` has pages for ``length`` tokens,
        compressing other slots' cold pages on demand when the f32 free
        list runs short."""
        c = self.config
        if length > c.max_len:
            raise ValueError(f"length {length} exceeds max_len {c.max_len}")
        need = -(-int(length) // c.page_size)
        have = int(self._allocated[slot])
        if need > have:
            short = need - have - len(self._free)
            if short > 0 and self.compress:
                self._reclaim(short, exclude=slot)
            if need - have > len(self._free):
                raise RuntimeError(
                    f"KV page pool exhausted: slot {slot} needs "
                    f"{need - have} page(s), {len(self._free)} free")
            for i in range(have, need):
                self.page_table[slot, i] = self._free.pop()
            self._allocated[slot] = need

    def _reclaim(self, pages: int, exclude: Optional[int] = None) -> int:
        """Compress cold pages across slots until ``pages`` f32 pages
        came back (or candidates ran out).  Returns pages reclaimed."""
        got = 0
        for slot in self._cold_candidates(exclude=exclude):
            if got >= pages:
                break
            got += self.compress_cold(
                slot, max_pages=pages - got)
        return got

    def compress_cold(self, slot: int, max_pages: Optional[int] = None
                      ) -> int:
        """Migrate up to ``max_pages`` of ``slot``'s cold pages into the
        e4m3 pool (prefix order -- compression always extends the cold
        prefix), returning their f32 pages to the free list.  The freed
        f32 table entries are pointed at the scratch page; gathers never
        read them (``comp_mask`` blends the e4m3 page in) but a sound
        table beats a dangling one."""
        if not self.compress:
            raise RuntimeError("cache built without compress=True")
        c = self.config
        n = self._cold_count(slot)
        if max_pages is not None:
            n = min(n, max_pages)
        n = min(n, len(self._cfree))
        if n <= 0:
            return 0
        start = int(self._cheld[slot])
        idxs = list(range(start, start + n))
        pids = np.asarray([self.page_table[slot, i] for i in idxs],
                          np.int32)
        cpids = np.asarray([self._cfree.pop() for _ in idxs], np.int32)
        dev_pids = jnp.asarray(pids)
        kq, ksc = _quantize_pages(self.k, dev_pids)
        vq, vsc = _quantize_pages(self.v, dev_pids)
        cp = jnp.asarray(cpids)
        self.kq = self.kq.at[:, cp].set(kq)
        self.vq = self.vq.at[:, cp].set(vq)
        self.kscale = self.kscale.at[:, cp].set(ksc)
        self.vscale = self.vscale.at[:, cp].set(vsc)
        for i, cpid, pid in zip(idxs, cpids, pids):
            self.cpage_table[slot, i] = cpid
            self.comp_mask[slot, i] = True
            self.page_table[slot, i] = c.scratch_page
            self._free.append(int(pid))
        self._cheld[slot] = start + n
        return n

    def free_slot(self, slot: int) -> None:
        """Return the slot's pages to the pool and mark it idle.  Page
        CONTENTS are deliberately left in place: the masking contract,
        not zeroing, is what guarantees no stale attention mass."""
        n = int(self._allocated[slot])
        for i in range(n - 1, -1, -1):
            if self.compress and self.comp_mask[slot, i]:
                self._cfree.append(int(self.cpage_table[slot, i]))
                self.comp_mask[slot, i] = False
            else:
                self._free.append(int(self.page_table[slot, i]))
        self._allocated[slot] = 0
        if self.compress:
            self._cheld[slot] = 0
        self.lengths[slot] = 0

    def release_all(self) -> int:
        """Free every slot and return how many pages that recovered.

        The drain path frees each suspended slot individually, so a
        healthy shrink sees ``release_all() == 0`` afterwards -- the
        control-plane tests use that as the exact-release check (a
        non-zero return means a slot leaked its pages past the drain).
        """
        freed = 0
        for slot in range(self.config.slots):
            n = int(self._allocated[slot])
            if n:
                freed += n
                self.free_slot(slot)
        return freed

    # -- device writes -----------------------------------------------------
    def write_prefill(self, slot: int, k_layers, v_layers) -> None:
        """Scatter a prefilled prompt's K/V into the slot's pages.

        ``k_layers``/``v_layers``: ``[num_layers, t, num_kv_heads,
        head_dim]`` (post-RoPE, as the decode step expects).  Reserves
        pages for ``t`` tokens and sets ``lengths[slot] = t``.
        """
        c = self.config
        t = int(k_layers.shape[1])
        self.reserve(slot, t)
        pos = np.arange(t)
        pages = jnp.asarray(self.page_table[slot][pos // c.page_size])
        offs = jnp.asarray(pos % c.page_size)
        dt = jnp.dtype(c.dtype)
        # One scatter per pool: [L, t, H, D] lands at (page, off) pairs.
        self.k = self.k.at[:, pages, offs].set(k_layers.astype(dt))
        self.v = self.v.at[:, pages, offs].set(v_layers.astype(dt))
        self.lengths[slot] = t

    def grow(self, slot: int) -> None:
        """Account one decoded token (the decode step already wrote its
        K/V in-step); reserves the next page at a boundary crossing."""
        new_len = int(self.lengths[slot]) + 1
        self.reserve(slot, new_len)
        self.lengths[slot] = new_len

    # -- step operands -----------------------------------------------------
    def table_device(self) -> jnp.ndarray:
        # np.array copy matters: jnp.asarray of host numpy is zero-copy
        # on CPU, so the device operand would ALIAS the mutable host
        # table and later host updates would race the dispatched step.
        return jnp.asarray(np.array(self.page_table))

    def lengths_device(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.lengths))

    def ctable_device(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.cpage_table))

    def cmask_device(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.comp_mask))

    def compress_operands(self) -> tuple:
        """The six extra step operands a ``compress=True`` decode/verify
        step takes after ``active`` (pools, scales, table, mask)."""
        return (self.kq, self.vq, self.kscale, self.vscale,
                self.ctable_device(), self.cmask_device())

    def layout(self) -> dict:
        return self.config.layout()


def _quantize_pages(pool, pids):
    """fp8-quantize pages ``pids`` of one pool through the PR 5 codec:
    one max-abs e4m3 scale per (layer, page, offset) row over the
    ``[kv_heads * head_dim]`` vector, so a never-written row (absmax 0)
    roundtrips to exact zeros with scale 1.  Returns
    ``(q [L, n, page, H, D] e4m3, scales [L, n, page] f32)``."""
    x = pool[:, pids]
    l, n, pg, hh, dd = x.shape
    q, s = fp8_quantize(x.reshape(l * n * pg, hh * dd), axis=0)
    return q.reshape(l, n, pg, hh, dd), s.reshape(l, n, pg)


def cache_sharding(mesh, tp_axis: str = "tp"):
    """NamedSharding splitting the kv-head dim over ``tp`` (dims:
    layers, pages, page_size, kv_heads, head_dim)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if mesh is None:
        return None
    return NamedSharding(mesh, P(None, None, None, tp_axis, None))
