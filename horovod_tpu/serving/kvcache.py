"""Sharded paged KV cache for the serving data plane.

Physical layout is a fixed page pool per layer::

    k, v: [num_layers, num_pages, page_size, num_kv_heads, head_dim]

sharded over the ``tp`` mesh axis on the kv-head dim (the same split the
tensor-parallel decode step gives the attention projections, so a rank's
cache shard pairs exactly with its ``wk``/``wv`` kernel shards and no
cross-rank traffic ever touches the cache).  The LOGICAL view -- which
pages belong to which batch slot, and how many tokens are live -- is
host-side metadata: an int32 ``page_table[slots, pages_per_slot]`` plus a
``lengths[slots]`` vector, shipped into the compiled step as plain
replicated operands.  Correctness never depends on page contents being
zeroed: every read masks positions ``>= lengths`` through
:func:`horovod_tpu.ops.attention.decode_attention`, so a recycled page's
stale keys are unreachable by construction (the eviction/reuse test
asserts this bit-for-bit).

Pages are allocated lazily from a free list as a slot's sequence grows
and returned wholesale on eviction -- continuous batching recycles slots
mid-flight, so the pool, not the slot count, bounds resident KV bytes.

fp8 cold-page compression (``CacheConfig(compress=True)``): pages that
sit ``hot_pages`` full pages behind a slot's write head are *cold* --
decode only reads them, never writes them again while the slot lives.
A cold page can be migrated into a parallel e4m3 pool through the PR 5
fp8 codec (:func:`~horovod_tpu.collectives.compression.fp8_quantize`,
one max-abs scale per token-layer row so an all-zero row roundtrips to
exact zeros), after which its f32 page returns to the free list.  The
decode/verify steps blend the two pools on gather (``comp_mask`` picks
the dequantised e4m3 page), so compression is invisible to the masking
contract: a recycled compressed page's stale bytes are unreachable for
exactly the reason a recycled f32 page's are.  Admission is therefore
page-gated on COMPRESSED size: ``can_admit``/``reserve`` count cold
pages at their e4m3 cost (compressing on demand to reclaim f32 pages),
so the same physical pool admits roughly 4x the cold-token residency.

Prefix sharing (PR 16): pages are REFCOUNTED, so one physical page can
back the same token prefix in many slots at once.  A page popped off
the free list starts at refcount 1 (its slot); :meth:`attach_pages`
maps an existing page into another slot's table with refcount +1, and
:meth:`free_slot` is a refcount DECREMENT -- the page returns to the
free list only when its last holder lets go.  Shared pages are
immutable by construction: decode/verify only write at positions ``>=
lengths``, which always land past a matched prefix, and every write
path additionally runs a copy-on-write guard (:meth:`reserve` with
``writable_from``, :meth:`write_prefill`) that clones a still-shared
page into a private one before the first byte changes -- a divergent
continuation can NEVER mutate the shared original (asserted bitwise in
tests/test_serving.py).  On top sits :class:`PrefixCache`: a radix
tree over page-sized token-id chunks mapping shared prompt prefixes
(system prompts, RAG templates, multi-turn session context) to resident
pages, with session pinning, TTL expiry, the fp8 pool as its demotion
tier, and LRU eviction under page pressure.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..collectives.compression import fp8_quantize
from ..timeline.metrics import registry as _registry


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Static shape of the pool (identical on every rank and mesh size)."""

    num_layers: int
    num_kv_heads: int
    head_dim: int
    slots: int
    page_size: int
    max_len: int
    dtype: str = "float32"
    compress: bool = False         # fp8 cold-page compression on/off
    hot_pages: int = 1             # full pages behind the head kept f32

    def __post_init__(self):
        if self.max_len % self.page_size:
            raise ValueError(
                f"max_len {self.max_len} not a multiple of page_size "
                f"{self.page_size}")
        if self.hot_pages < 0:
            raise ValueError(f"hot_pages must be >= 0: {self.hot_pages}")

    @property
    def pages_per_slot(self) -> int:
        return self.max_len // self.page_size

    @property
    def num_pages(self) -> int:
        return self.slots * self.pages_per_slot

    @property
    def scratch_page(self) -> int:
        """Index of the write sink: the decode step writes EVERY slot's
        K/V unconditionally (fixed-shape batch), so idle slots are
        redirected to this extra page past the allocatable pool instead
        of clobbering page 0."""
        return self.num_pages

    def layout(self) -> dict:
        """GLOBAL layout descriptor.  Mesh-size invariant by contract:
        the pool shape, page table geometry and dtype never depend on
        how many ranks the kv-head dim is split over (asserted by
        tests/test_serving.py across 1- and 8-device meshes)."""
        return {
            "kv_shape": [self.num_layers, self.num_pages + 1,
                         self.page_size, self.num_kv_heads, self.head_dim],
            "page_table_shape": [self.slots, self.pages_per_slot],
            "page_size": self.page_size,
            "pages_per_slot": self.pages_per_slot,
            "num_pages": self.num_pages,
            "scratch_page": self.scratch_page,
            "dtype": str(jnp.dtype(self.dtype)),
        }


class PagedKVCache:
    """Device page pool + host page table / free list for one model."""

    def __init__(self, config: CacheConfig, sharding=None):
        self.config = config
        c = config
        # +1: trailing scratch page, the write sink for idle slots.
        shape = (c.num_layers, c.num_pages + 1, c.page_size,
                 c.num_kv_heads, c.head_dim)
        k = jnp.zeros(shape, jnp.dtype(c.dtype))
        v = jnp.zeros(shape, jnp.dtype(c.dtype))
        if sharding is not None:
            k = jax.device_put(k, sharding)
            v = jax.device_put(v, sharding)
        self.sharding = sharding
        self.k = k
        self.v = v
        # Host-side logical view.  Unallocated table entries point at
        # page 0 -- harmless, reads beyond ``lengths`` are masked.
        self.page_table = np.zeros((c.slots, c.pages_per_slot), np.int32)
        self.lengths = np.zeros((c.slots,), np.int32)
        self._allocated = np.zeros((c.slots,), np.int32)  # pages per slot
        self._free = list(range(c.num_pages - 1, -1, -1))  # pop() -> 0, 1...
        # Holders per physical page: 0 = on the free list, 1 = private,
        # >1 = shared across slots and/or pinned by the prefix tree.
        self._refcount = np.zeros((c.num_pages,), np.int32)
        # Optional page-pressure hook (PrefixCache installs itself
        # here): called with the page shortfall before admission or
        # reservation gives up, so cached-but-unreferenced prefixes are
        # demoted/evicted instead of blocking live traffic.
        self.reclaim_cb = None
        # fp8 cold-page pool: a parallel e4m3 page space plus one max-abs
        # scale per (layer, page, offset) row, blended in on gather by the
        # decode/verify steps wherever ``comp_mask`` is set.
        self.compress = bool(c.compress)
        if self.compress:
            self.kq = jnp.zeros(shape, jnp.float8_e4m3fn)
            self.vq = jnp.zeros(shape, jnp.float8_e4m3fn)
            if sharding is not None:
                self.kq = jax.device_put(self.kq, sharding)
                self.vq = jax.device_put(self.vq, sharding)
            sshape = (c.num_layers, c.num_pages + 1, c.page_size)
            self.kscale = jnp.ones(sshape, jnp.float32)
            self.vscale = jnp.ones(sshape, jnp.float32)
            self.cpage_table = np.zeros((c.slots, c.pages_per_slot),
                                        np.int32)
            self.comp_mask = np.zeros((c.slots, c.pages_per_slot), bool)
            self._cfree = list(range(c.num_pages - 1, -1, -1))
            self._cheld = np.zeros((c.slots,), np.int32)
            self._crefcount = np.zeros((c.num_pages,), np.int32)

    # -- page accounting ---------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        """f32 pages currently held by slots (free_pages +
        allocated_pages == num_pages is the pool invariant the drain
        tests assert; compressed pages live in the e4m3 pool and are
        accounted by :attr:`compressed_pages`)."""
        total = int(self._allocated.sum())
        if self.compress:
            total -= int(self._cheld.sum())
        return total

    @property
    def compressed_pages(self) -> int:
        return int(self._cheld.sum()) if self.compress else 0

    @property
    def live_pages(self) -> int:
        """Physical f32 pages with at least one holder.  The pool
        invariant under sharing is ``free_pages + live_pages ==
        num_pages`` (``allocated_pages`` counts TABLE ENTRIES and
        double-counts a page shared by two slots)."""
        return int((self._refcount > 0).sum())

    def refcounts_balanced(self) -> bool:
        """True when every page is either on a free list (refcount 0)
        or held (refcount > 0) with the free lists consistent -- the
        drain-time leak check the BENCH_r17 drill asserts."""
        ok = len(self._free) + self.live_pages == self.config.num_pages
        ok = ok and not any(self._refcount[p] for p in self._free)
        if self.compress:
            live_c = int((self._crefcount > 0).sum())
            ok = ok and len(self._cfree) + live_c == self.config.num_pages
            ok = ok and not any(self._crefcount[p] for p in self._cfree)
        return bool(ok)

    # -- refcount primitives ----------------------------------------------
    def add_page_ref(self, pid: int, kind: str = "f") -> None:
        if kind == "c":
            self._crefcount[pid] += 1
        else:
            self._refcount[pid] += 1

    def drop_page_ref(self, pid: int, kind: str = "f") -> bool:
        """Drop one holder; returns True when that freed the physical
        page (last reference gone -- the page rejoins its free list
        unzeroed, the masking contract keeps its stale bytes dark)."""
        if kind == "c":
            self._crefcount[pid] -= 1
            if self._crefcount[pid] == 0:
                self._cfree.append(int(pid))
                return True
            return False
        self._refcount[pid] -= 1
        if self._refcount[pid] == 0:
            self._free.append(int(pid))
            return True
        return False

    @property
    def resident_bytes(self) -> int:
        """Logical KV residency at COMPRESSED accounting: f32 pages at
        full price, cold e4m3 pages at one byte per element plus the
        per-row f32 scale (the number ``can_admit`` effectively budgets
        against)."""
        c = self.config
        row = c.num_kv_heads * c.head_dim
        page_f32 = c.num_layers * c.page_size * row * 2 \
            * jnp.dtype(c.dtype).itemsize
        page_fp8 = c.num_layers * c.page_size * (row + 4) * 2
        return (self.allocated_pages * page_f32
                + self.compressed_pages * page_fp8)

    def _cold_candidates(self, exclude: Optional[int] = None
                         ) -> List[int]:
        """Slots ordered by how many not-yet-compressed cold pages they
        hold (descending) -- the reclaim sweep order."""
        c = self.config
        out = []
        for slot in range(c.slots):
            if slot == exclude:
                continue
            n = self._cold_count(slot)
            if n > 0:
                out.append((n, slot))
        return [slot for _, slot in sorted(out, reverse=True)]

    def _cold_indices(self, slot: int) -> List[int]:
        """Table indices of ``slot``'s cold pages still resident in
        f32: full pages at least ``hot_pages`` behind the write head
        that are not yet compressed and not SHARED (migrating a page
        another holder still reads through the f32 table would dangle
        their gather).  Pages at or past ``lengths`` are NEVER cold --
        the decode/verify steps may still write them (speculative
        rejects roll ``lengths`` back below already-written
        positions)."""
        c = self.config
        full = int(self.lengths[slot]) // c.page_size
        out = []
        for i in range(max(0, full - c.hot_pages)):
            if self.comp_mask[slot, i]:
                continue
            if self._refcount[int(self.page_table[slot, i])] != 1:
                continue
            out.append(i)
        return out

    def _cold_count(self, slot: int) -> int:
        return len(self._cold_indices(slot))

    def can_admit(self, length: int) -> bool:
        """Whether a sequence of ``length`` tokens fits the pool now.

        With compression the gate prices cold pages at their compressed
        size: f32 pages reclaimable by a cold sweep (bounded by e4m3
        pool headroom) count as free.  Under page pressure the prefix
        tree's ``reclaim_cb`` is asked to demote/evict unreferenced
        cached prefixes first -- live traffic always outranks cache
        residency."""

        def avail() -> int:
            a = len(self._free)
            if self.compress:
                cold = sum(self._cold_count(s)
                           for s in range(self.config.slots))
                a += min(cold, len(self._cfree))
            return a

        need = -(-max(int(length), 1) // self.config.page_size)
        if need > avail() and self.reclaim_cb is not None:
            self.reclaim_cb(need - avail())
        return need <= avail()

    def reserve(self, slot: int, length: int,
                writable_from: Optional[int] = None) -> None:
        """Ensure slot ``slot`` has pages for ``length`` tokens,
        compressing other slots' cold pages on demand when the f32 free
        list runs short.

        ``writable_from``: token position of the first upcoming WRITE
        (the decode step's append point).  Every page covering
        ``writable_from ..`` is made private first -- the copy-on-write
        guard for shared prefix pages."""
        c = self.config
        if length > c.max_len:
            raise ValueError(f"length {length} exceeds max_len {c.max_len}")
        need = -(-int(length) // c.page_size)
        have = int(self._allocated[slot])
        if need > have:
            short = need - have - len(self._free)
            if short > 0 and self.reclaim_cb is not None:
                self.reclaim_cb(short)
                short = need - have - len(self._free)
            if short > 0 and self.compress:
                self._reclaim(short, exclude=slot)
            if need - have > len(self._free):
                raise RuntimeError(
                    f"KV page pool exhausted: slot {slot} needs "
                    f"{need - have} page(s), {len(self._free)} free")
            for i in range(have, need):
                pid = self._free.pop()
                self._refcount[pid] = 1
                self.page_table[slot, i] = pid
            self._allocated[slot] = need
        if writable_from is not None:
            self._make_writable(slot, writable_from)

    def _make_writable(self, slot: int, from_pos: int) -> None:
        """Copy-on-write guard: clone every still-shared page covering
        positions ``>= from_pos`` into a private page before the slot
        writes there.  The shared original is never mutated -- holders
        reading it through the tree or another slot keep seeing the
        exact bytes they attached (bitwise, by construction: the write
        lands in the clone)."""
        c = self.config
        for i in range(int(from_pos) // c.page_size,
                       int(self._allocated[slot])):
            if self.compress and self.comp_mask[slot, i]:
                raise RuntimeError(
                    f"slot {slot} page {i} is fp8-demoted inside the "
                    "write range; demotion must stay strictly below "
                    "the write head")
            pid = int(self.page_table[slot, i])
            if self._refcount[pid] <= 1:
                continue
            if not self._free and self.reclaim_cb is not None:
                self.reclaim_cb(1)
            if not self._free and self.compress:
                self._reclaim(1, exclude=slot)
            if not self._free:
                raise RuntimeError(
                    "KV page pool exhausted during copy-on-write "
                    f"divergence of slot {slot}")
            new = self._free.pop()
            self._refcount[new] = 1
            self.k = self.k.at[:, new].set(self.k[:, pid])
            self.v = self.v.at[:, new].set(self.v[:, pid])
            self.page_table[slot, i] = new
            self.drop_page_ref(pid)

    def _reclaim(self, pages: int, exclude: Optional[int] = None) -> int:
        """Compress cold pages across slots until ``pages`` f32 pages
        came back (or candidates ran out).  Returns pages reclaimed."""
        got = 0
        for slot in self._cold_candidates(exclude=exclude):
            if got >= pages:
                break
            got += self.compress_cold(
                slot, max_pages=pages - got)
        return got

    def compress_cold(self, slot: int, max_pages: Optional[int] = None
                      ) -> int:
        """Migrate up to ``max_pages`` of ``slot``'s cold pages into the
        e4m3 pool (lowest table index first -- compression grows from
        the prefix end; shared pages are skipped, other holders still
        read them through f32), returning their f32 pages to the free
        list.  The freed f32 table entries are pointed at the scratch
        page; gathers never read them (``comp_mask`` blends the e4m3
        page in) but a sound table beats a dangling one."""
        if not self.compress:
            raise RuntimeError("cache built without compress=True")
        c = self.config
        idxs = self._cold_indices(slot)
        if max_pages is not None:
            idxs = idxs[:max_pages]
        idxs = idxs[:len(self._cfree)]
        if not idxs:
            return 0
        pids = np.asarray([self.page_table[slot, i] for i in idxs],
                          np.int32)
        cpids = np.asarray([self._cfree.pop() for _ in idxs], np.int32)
        dev_pids = jnp.asarray(pids)
        kq, ksc = _quantize_pages(self.k, dev_pids)
        vq, vsc = _quantize_pages(self.v, dev_pids)
        cp = jnp.asarray(cpids)
        self.kq = self.kq.at[:, cp].set(kq)
        self.vq = self.vq.at[:, cp].set(vq)
        self.kscale = self.kscale.at[:, cp].set(ksc)
        self.vscale = self.vscale.at[:, cp].set(vsc)
        for i, cpid, pid in zip(idxs, cpids, pids):
            self.cpage_table[slot, i] = cpid
            self.comp_mask[slot, i] = True
            self._crefcount[cpid] = 1
            self.page_table[slot, i] = c.scratch_page
            self.drop_page_ref(int(pid))
        self._cheld[slot] += len(idxs)
        return len(idxs)

    def free_slot(self, slot: int) -> None:
        """Refcount-decrement the slot's pages and mark it idle.  A
        private page rejoins the free list immediately; a SHARED page
        (prefix tree or another slot still holds it) stays resident
        until its last reference drops.  Page CONTENTS are deliberately
        left in place either way: the masking contract, not zeroing, is
        what guarantees no stale attention mass."""
        n = int(self._allocated[slot])
        for i in range(n - 1, -1, -1):
            if self.compress and self.comp_mask[slot, i]:
                self.drop_page_ref(int(self.cpage_table[slot, i]), "c")
                self.comp_mask[slot, i] = False
            else:
                self.drop_page_ref(int(self.page_table[slot, i]))
        self._allocated[slot] = 0
        if self.compress:
            self._cheld[slot] = 0
        self.lengths[slot] = 0

    def release_all(self) -> int:
        """Free every slot and return how many pages that recovered.

        The drain path frees each suspended slot individually, so a
        healthy shrink sees ``release_all() == 0`` afterwards -- the
        control-plane tests use that as the exact-release check (a
        non-zero return means a slot leaked its pages past the drain).
        """
        freed = 0
        for slot in range(self.config.slots):
            n = int(self._allocated[slot])
            if n:
                freed += n
                self.free_slot(slot)
        return freed

    # -- prefix sharing ----------------------------------------------------
    def attach_pages(self, slot: int,
                     entries: Sequence[Tuple[str, int]],
                     length: int) -> None:
        """Map already-resident pages into an EMPTY slot's table with
        refcount +1 each -- the prefix-cache hit path: the matched
        prefix's K/V is live without a single prefill FLOP.  Entries
        are ``("f", page)`` f32 or ``("c", cpage)`` fp8-demoted; the
        slot's first ``length`` tokens (``len(entries)`` full pages)
        are then readable and the tail prefill continues at ``start=
        length`` via :meth:`write_prefill`."""
        c = self.config
        if int(self._allocated[slot]):
            raise RuntimeError(
                f"attach_pages: slot {slot} is not empty")
        if len(entries) * c.page_size != int(length):
            raise ValueError(
                f"attach_pages: {len(entries)} page(s) cannot back "
                f"{length} tokens at page_size {c.page_size}")
        for i, (kind, pid) in enumerate(entries):
            if kind == "c":
                if not self.compress:
                    raise RuntimeError(
                        "compressed prefix entry on a compress=False "
                        "cache")
                self.cpage_table[slot, i] = pid
                self.comp_mask[slot, i] = True
                self.page_table[slot, i] = c.scratch_page
                self._cheld[slot] += 1
            else:
                self.page_table[slot, i] = pid
            self.add_page_ref(pid, kind)
        self._allocated[slot] = len(entries)
        self.lengths[slot] = int(length)

    def adopt_pages(self, k_pages, v_pages) -> List[Tuple[str, int]]:
        """Materialize STREAMED full pages (``[L, n, page_size, H, D]``,
        the ``serving.kvwire`` f32 tier) as resident pool pages at
        refcount 1, owned by the caller.  The disaggregated import path
        then maps them into a slot with :meth:`attach_pages` and drops
        the importer's reference -- exactly the prefix-hit flow, except
        the bytes arrived over the rendezvous KV plane instead of being
        computed here.  Contents are written verbatim (no requantize,
        no cast beyond the pool dtype), so an f32-tier import is
        bitwise identical to a local ``write_prefill``."""
        k_pages = np.asarray(k_pages)
        n = int(k_pages.shape[1])
        if n == 0:
            return []
        short = n - len(self._free)
        if short > 0 and self.reclaim_cb is not None:
            self.reclaim_cb(short)
            short = n - len(self._free)
        if short > 0 and self.compress:
            self._reclaim(short)
        if n > len(self._free):
            raise RuntimeError(
                f"KV page pool exhausted: adopting {n} streamed "
                f"page(s), {len(self._free)} free")
        pids = np.asarray([self._free.pop() for _ in range(n)], np.int32)
        for pid in pids:
            self._refcount[pid] = 1
        dt = jnp.dtype(self.config.dtype)
        dev = jnp.asarray(pids)
        self.k = self.k.at[:, dev].set(jnp.asarray(k_pages, dt))
        self.v = self.v.at[:, dev].set(jnp.asarray(np.asarray(v_pages),
                                                   dt))
        return [("f", int(p)) for p in pids]

    def adopt_compressed_pages(self, kq, vq, kscale, vscale
                               ) -> List[Tuple[str, int]]:
        """fp8 twin of :meth:`adopt_pages`: land streamed e4m3 pages +
        per-row scales (the ``serving.kvwire`` fp8 tier, the PR 14
        cold-page codec) straight into the compressed pool at refcount
        1.  Because the wire quantization reuses ``_quantize_pages``'s
        exact reshape/axis, an imported page is bit-identical to
        :meth:`demote_page` of the same resident bytes -- the decode
        gather blend cannot tell the two apart."""
        if not self.compress:
            raise RuntimeError("cache built without compress=True")
        kq = np.asarray(kq)
        n = int(kq.shape[1])
        if n == 0:
            return []
        if n > len(self._cfree):
            raise RuntimeError(
                f"e4m3 pool exhausted: adopting {n} streamed cold "
                f"page(s), {len(self._cfree)} free")
        cpids = np.asarray([self._cfree.pop() for _ in range(n)],
                           np.int32)
        for cpid in cpids:
            self._crefcount[cpid] = 1
        cp = jnp.asarray(cpids)
        self.kq = self.kq.at[:, cp].set(
            jnp.asarray(kq, jnp.float8_e4m3fn))
        self.vq = self.vq.at[:, cp].set(
            jnp.asarray(np.asarray(vq), jnp.float8_e4m3fn))
        self.kscale = self.kscale.at[:, cp].set(
            jnp.asarray(np.asarray(kscale), jnp.float32))
        self.vscale = self.vscale.at[:, cp].set(
            jnp.asarray(np.asarray(vscale), jnp.float32))
        return [("c", int(p)) for p in cpids]

    def gather_pages(self, entries: Sequence[Tuple[str, int]]) -> tuple:
        """Materialize page contents as chunked-prefill ``past``
        operands: ``(k, v)`` each ``[num_layers, 1, n * page_size,
        num_kv_heads, head_dim]``, fp8-demoted pages dequantized
        through their per-row scales (same blend the decode gather
        does)."""
        c = self.config
        fp = np.asarray([pid if kind == "f" else c.scratch_page
                         for kind, pid in entries], np.int32)
        any_c = any(kind == "c" for kind, _ in entries)
        cp = np.asarray([pid if kind == "c" else 0
                         for kind, pid in entries], np.int32)
        cmask = np.asarray([kind == "c" for kind, _ in entries], bool)
        out = []
        for pool, qpool, scale in (
                (self.k, getattr(self, "kq", None),
                 getattr(self, "kscale", None)),
                (self.v, getattr(self, "vq", None),
                 getattr(self, "vscale", None))):
            view = pool[:, jnp.asarray(fp)]        # [L, n, ps, H, D]
            if any_c:
                cpd = jnp.asarray(cp)
                deq = (qpool[:, cpd].astype(jnp.float32)
                       * scale[:, cpd][..., None, None]).astype(
                           view.dtype)
                view = jnp.where(
                    jnp.asarray(cmask)[None, :, None, None, None],
                    deq, view)
            l, n, ps, hh, dd = view.shape
            out.append(view.reshape(l, n * ps, hh, dd)[:, None])
        return tuple(out)

    def demote_page(self, pid: int) -> int:
        """Quantize ONE tree-held f32 page into the e4m3 pool (the PR
        14 codec) and return the compressed page id at refcount 1.  The
        caller drops its f32 reference afterwards -- the prefix tree's
        demotion tier under page pressure."""
        if not self.compress:
            raise RuntimeError("cache built without compress=True")
        if not self._cfree:
            raise RuntimeError("e4m3 pool exhausted")
        cpid = int(self._cfree.pop())
        dev = jnp.asarray(np.asarray([pid], np.int32))
        kq, ksc = _quantize_pages(self.k, dev)
        vq, vsc = _quantize_pages(self.v, dev)
        cp = jnp.asarray(np.asarray([cpid], np.int32))
        self.kq = self.kq.at[:, cp].set(kq)
        self.vq = self.vq.at[:, cp].set(vq)
        self.kscale = self.kscale.at[:, cp].set(ksc)
        self.vscale = self.vscale.at[:, cp].set(vsc)
        self._crefcount[cpid] = 1
        return cpid

    # -- device writes -----------------------------------------------------
    def write_prefill(self, slot: int, k_layers, v_layers,
                      start: int = 0) -> None:
        """Scatter a prefilled prompt's K/V into the slot's pages.

        ``k_layers``/``v_layers``: ``[num_layers, t, num_kv_heads,
        head_dim]`` (post-RoPE, as the decode step expects).  Reserves
        pages for ``start + t`` tokens and sets ``lengths[slot] =
        start + t``.  ``start`` is the prefix-cache seam: a matched
        prefix's pages are already attached and immutable, only the
        tail ``[start:]`` is scattered (through the copy-on-write
        guard, so a partial shared page is cloned first)."""
        c = self.config
        t = int(k_layers.shape[1])
        self.reserve(slot, start + t, writable_from=start)
        pos = np.arange(start, start + t)
        pages = jnp.asarray(self.page_table[slot][pos // c.page_size])
        offs = jnp.asarray(pos % c.page_size)
        dt = jnp.dtype(c.dtype)
        # One scatter per pool: [L, t, H, D] lands at (page, off) pairs.
        self.k = self.k.at[:, pages, offs].set(k_layers.astype(dt))
        self.v = self.v.at[:, pages, offs].set(v_layers.astype(dt))
        self.lengths[slot] = start + t

    def grow(self, slot: int) -> None:
        """Account one decoded token (the decode step already wrote its
        K/V in-step); reserves the next page at a boundary crossing."""
        new_len = int(self.lengths[slot]) + 1
        self.reserve(slot, new_len, writable_from=new_len - 1)
        self.lengths[slot] = new_len

    # -- step operands -----------------------------------------------------
    def table_device(self) -> jnp.ndarray:
        # np.array copy matters: jnp.asarray of host numpy is zero-copy
        # on CPU, so the device operand would ALIAS the mutable host
        # table and later host updates would race the dispatched step.
        return jnp.asarray(np.array(self.page_table))

    def lengths_device(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.lengths))

    def ctable_device(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.cpage_table))

    def cmask_device(self) -> jnp.ndarray:
        return jnp.asarray(np.array(self.comp_mask))

    def compress_operands(self) -> tuple:
        """The six extra step operands a ``compress=True`` decode/verify
        step takes after ``active`` (pools, scales, table, mask)."""
        return (self.kq, self.vq, self.kscale, self.vscale,
                self.ctable_device(), self.cmask_device())

    def layout(self) -> dict:
        return self.config.layout()


class _PrefixNode:
    """One full page of prompt tokens in the radix tree.  ``key`` is
    the page's token-id tuple, ``page`` the backing page id (``kind``
    ``"f"`` f32 or ``"c"`` fp8-demoted), ``touch`` the LRU clock,
    ``pins`` the live-session pin count."""

    __slots__ = ("key", "parent", "children", "kind", "page", "touch",
                 "pins", "dead")

    def __init__(self, key, parent, kind, page, touch):
        self.key = key
        self.parent = parent
        self.children: Dict[tuple, "_PrefixNode"] = {}
        self.kind = kind
        self.page = page
        self.touch = touch
        self.pins = 0
        self.dead = False


class PrefixCache:
    """Radix tree over token-id prefixes -> refcounted KV pages.

    The tree's unit is one FULL page (``page_size`` token ids); a
    request's prompt is matched page-chunk by page-chunk, and every
    matched chunk's K/V is already resident -- :meth:`match` +
    :meth:`PagedKVCache.attach_pages` make the whole matched prefix
    live with zero prefill FLOPs, only the tail runs through the PR 14
    chunked flash prefill.  After a prefill the slot's full prompt
    pages are :meth:`insert`-ed, so the NEXT request sharing the prefix
    hits (the tree holds its own +1 reference per page; tree-held pages
    survive ``free_slot``).

    Multi-turn sessions: :meth:`pin_session` pins the node path of a
    session's context so it stays warm across requests; pins expire
    after ``session_ttl_steps`` engine steps without reuse
    (:meth:`tick`).  Under page pressure (:meth:`release_pages`,
    installed as the cache's ``reclaim_cb``) tree-only f32 pages are
    first DEMOTED into the fp8 cold-page pool (still matchable, ~4x
    cheaper), then evicted leaf-first in LRU order -- unpinned entries
    before pinned ones, so live sessions are the last thing page
    pressure takes.
    """

    def __init__(self, cache: PagedKVCache,
                 session_ttl_steps: int = 0):
        self.cache = cache
        self.session_ttl_steps = int(session_ttl_steps)
        self._children: Dict[tuple, _PrefixNode] = {}
        self._clock = 0
        self._sessions: "collections.OrderedDict[object, dict]" = \
            collections.OrderedDict()
        self.queries = 0
        self.hits = 0
        self.nodes = 0
        reg = _registry()
        self._g_hit = reg.gauge(
            "horovod_serving_prefix_hit_rate",
            "Fraction of prefill queries that matched a cached prefix")
        self._g_pages = reg.gauge(
            "horovod_serving_prefix_pages",
            "KV pages pinned by the prefix tree")
        self._g_sessions = reg.gauge(
            "horovod_serving_sessions_live",
            "Sessions with pinned warm KV context")
        self._m_tok = reg.counter(
            "horovod_serving_prefix_tokens_total",
            "Prefill tokens by provenance (cached = prefill FLOPs "
            "avoided)", labelnames=("source",))
        cache.reclaim_cb = self.release_pages

    # -- stats -------------------------------------------------------------
    @property
    def hit_rate(self) -> float:
        return self.hits / self.queries if self.queries else 0.0

    @property
    def sessions_live(self) -> int:
        return len(self._sessions)

    def stats(self) -> dict:
        return {"queries": self.queries, "hits": self.hits,
                "hit_rate": self.hit_rate, "nodes": self.nodes,
                "sessions": len(self._sessions)}

    # -- the radix walk ----------------------------------------------------
    def _chunk(self, prompt, i: int) -> tuple:
        ps = self.cache.config.page_size
        return tuple(int(x) for x in prompt[i * ps:(i + 1) * ps])

    def match(self, prompt) -> Tuple[int, List[Tuple[str, int]]]:
        """Deepest cached prefix of ``prompt`` in full pages, capped at
        ``len(prompt) - 1`` tokens so the tail prefill always has at
        least one token to produce first-token logits from.  Returns
        ``(matched_tokens, [(kind, page), ...])`` ready for
        :meth:`PagedKVCache.attach_pages`."""
        ps = self.cache.config.page_size
        limit = (len(prompt) - 1) // ps
        entries: List[Tuple[str, int]] = []
        children = self._children
        for i in range(limit):
            node = children.get(self._chunk(prompt, i))
            if node is None:
                break
            node.touch = self._clock
            entries.append((node.kind, node.page))
            children = node.children
        self.queries += 1
        if entries:
            self.hits += 1
        matched = len(entries) * ps
        self._m_tok.labels(source="cached").inc(matched)
        self._m_tok.labels(source="computed").inc(len(prompt) - matched)
        self._g_hit.set(self.hit_rate)
        return matched, entries

    def insert(self, prompt, slot: int) -> int:
        """Register ``slot``'s resident full prompt pages under their
        token chunks (tree refcount +1 each); chunks already present
        are touched, not duplicated.  Returns newly registered pages."""
        cache = self.cache
        n = min(len(prompt), int(cache.lengths[slot])) \
            // cache.config.page_size
        children = self._children
        parent = None
        new = 0
        for i in range(n):
            key = self._chunk(prompt, i)
            node = children.get(key)
            if node is None:
                if cache.compress and cache.comp_mask[slot, i]:
                    kind, pid = "c", int(cache.cpage_table[slot, i])
                else:
                    kind, pid = "f", int(cache.page_table[slot, i])
                node = _PrefixNode(key, parent, kind, pid, self._clock)
                cache.add_page_ref(pid, kind)
                children[key] = node
                self.nodes += 1
                new += 1
            node.touch = self._clock
            parent = node
            children = node.children
        self._g_pages.set(self.nodes)
        return new

    # -- sessions ----------------------------------------------------------
    def pin_session(self, sid, prompt) -> None:
        """Pin the node path backing ``prompt``'s full pages under
        session id ``sid`` -- the multi-turn warm set.  Re-pinning the
        same session releases its previous pins first (the context
        grew) and refreshes its TTL."""
        nodes: List[_PrefixNode] = []
        children = self._children
        n = len(prompt) // self.cache.config.page_size
        for i in range(n):
            node = children.get(self._chunk(prompt, i))
            if node is None:
                break
            nodes.append(node)
            children = node.children
        old = self._sessions.pop(sid, None)
        if old is not None:
            for nd in old["nodes"]:
                if not nd.dead:
                    nd.pins -= 1
        for nd in nodes:
            nd.pins += 1
        self._sessions[sid] = {"nodes": nodes, "step": self._clock}
        self._g_sessions.set(len(self._sessions))

    def touch_session(self, sid) -> bool:
        """Refresh a session's TTL on reuse; True when it was warm."""
        entry = self._sessions.get(sid)
        if entry is None:
            return False
        entry["step"] = self._clock
        self._sessions.move_to_end(sid)
        return True

    def _expire_session(self, sid) -> None:
        entry = self._sessions.pop(sid)
        for nd in entry["nodes"]:
            if not nd.dead:
                nd.pins -= 1
        self._g_sessions.set(len(self._sessions))

    def tick(self, steps: int = 1) -> None:
        """Advance the LRU/TTL clock (one call per engine step).
        Sessions idle past ``session_ttl_steps`` lose their pins --
        their pages stay cached but become ordinary LRU fodder."""
        self._clock += int(steps)
        if not self.session_ttl_steps:
            return
        while self._sessions:
            sid, entry = next(iter(self._sessions.items()))
            if self._clock - entry["step"] <= self.session_ttl_steps:
                break
            self._expire_session(sid)

    # -- pressure: demote, then evict --------------------------------------
    def _iter_nodes(self):
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def _drop(self, node: _PrefixNode) -> bool:
        """Remove one leaf; True when its f32 page actually freed."""
        owner = self._children if node.parent is None \
            else node.parent.children
        owner.pop(node.key, None)
        node.dead = True
        self.nodes -= 1
        freed = self.cache.drop_page_ref(node.page, node.kind)
        self._g_pages.set(self.nodes)
        return freed and node.kind == "f"

    def _demote(self, need: int) -> int:
        """fp8 demotion tier: quantize LRU tree-only f32 pages into the
        cold pool, freeing their f32 pages while keeping the prefix
        matchable."""
        cache = self.cache
        if not cache.compress:
            return 0
        cand = [nd for nd in self._iter_nodes()
                if nd.kind == "f" and cache._refcount[nd.page] == 1]
        cand.sort(key=lambda nd: nd.touch)
        freed = 0
        for nd in cand:
            if freed >= need or not cache._cfree:
                break
            cpid = cache.demote_page(nd.page)
            if cache.drop_page_ref(nd.page):
                freed += 1
            nd.kind, nd.page = "c", cpid
        return freed

    def _evict(self, need: int) -> int:
        """LRU leaf eviction, unpinned entries strictly before pinned
        ones (a live session's warm set is the last thing to go)."""
        freed = 0
        for take_pinned in (False, True):
            while freed < need:
                leaves = [nd for nd in self._iter_nodes()
                          if not nd.children
                          and (nd.pins > 0) == take_pinned]
                if not leaves:
                    break
                if self._drop(min(leaves, key=lambda nd: nd.touch)):
                    freed += 1
            if freed >= need:
                break
        return freed

    def release_pages(self, need: int) -> int:
        """Give back ``need`` f32 pages to live traffic: demote first
        (residency survives at e4m3 cost), evict LRU after.  Installed
        as the cache's ``reclaim_cb``."""
        freed = self._demote(need)
        if freed < need:
            freed += self._evict(need - freed)
        return freed

    def drop_all(self) -> None:
        """Release every tree reference and session pin (drain/leak
        check: afterwards the pool must be fully free again)."""
        for sid in list(self._sessions):
            self._expire_session(sid)
        while True:
            leaves = [nd for nd in self._iter_nodes()
                      if not nd.children]
            if not leaves:
                break
            for nd in leaves:
                self._drop(nd)


def _quantize_pages(pool, pids):
    """fp8-quantize pages ``pids`` of one pool through the PR 5 codec:
    one max-abs e4m3 scale per (layer, page, offset) row over the
    ``[kv_heads * head_dim]`` vector, so a never-written row (absmax 0)
    roundtrips to exact zeros with scale 1.  Returns
    ``(q [L, n, page, H, D] e4m3, scales [L, n, page] f32)``."""
    x = pool[:, pids]
    l, n, pg, hh, dd = x.shape
    q, s = fp8_quantize(x.reshape(l * n * pg, hh * dd), axis=0)
    return q.reshape(l, n, pg, hh, dd), s.reshape(l, n, pg)


def cache_sharding(mesh, tp_axis: str = "tp"):
    """NamedSharding splitting the kv-head dim over ``tp`` (dims:
    layers, pages, page_size, kv_heads, head_dim)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if mesh is None:
        return None
    return NamedSharding(mesh, P(None, None, None, tp_axis, None))
