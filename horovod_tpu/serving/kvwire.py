"""Versioned wire codec for KV-page streaming (prefill -> decode).

Disaggregated serving splits one engine into a prefill worker and a
decode worker on separate (virtual) meshes; the only thing that moves
between them is a prompt's finished K/V pages, published as opaque
bytes over the rendezvous KV plane (``run/http_kv.py``).  This module
is the wire format: a framed, versioned, content-hashed payload that a
decode worker can land in its OWN :class:`~.kvcache.PagedKVCache` via
``adopt_pages`` + ``attach_pages``.

Two tiers, selected by ``HOROVOD_KV_PAGE_WIRE``:

* ``f32`` (default) -- full pages travel as the pool dtype's raw bytes.
  Import is BITWISE: the decode worker's pool holds exactly the bytes
  the prefill worker computed, so a disaggregated decode stream is
  bit-for-bit equal to a colocated engine's (the round-20 parity gate).
* ``fp8`` -- full pages travel through the PR 14 cold-page codec
  (:func:`~..collectives.compression.fp8_quantize`, one max-abs e4m3
  scale per (layer, page, offset) row), ~4x cheaper on the wire.  The
  quantization is performed with the SAME reshape/axis the in-pool
  ``demote_page`` path uses, so an imported fp8 page is bit-identical
  to demoting the equivalent resident page -- the decode step's gather
  blend cannot tell streamed cold pages from locally demoted ones.

The partial tail page (``length % page_size`` tokens) always travels
f32: a partial page is by definition at the write head, and the pool
never holds a hot page in e4m3 either.

Framing: ``b"HVKW" | u16 version | u32 header_len | header JSON |
payload``.  The header carries the geometry, the payload byte count
and a SHA-256 content hash; :func:`decode_kv` rejects a version
mismatch, a truncated payload, and a hash mismatch with distinct
``ValueError`` messages -- a half-written or stale KV entry must never
reach ``attach_pages``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import struct
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..collectives.compression import fp8_quantize
from ..core.config import _env

MAGIC = b"HVKW"
WIRE_VERSION = 1
TIER_F32 = "f32"
TIER_FP8 = "fp8"
_FRAME = struct.Struct("<4sHI")
_FP8_DTYPE = np.dtype(jnp.float8_e4m3fn)


def wire_tier() -> str:
    """Tier selected by ``HOROVOD_KV_PAGE_WIRE`` (``f32`` default)."""
    tier = (_env("KV_PAGE_WIRE") or TIER_F32).lower()
    if tier not in (TIER_F32, TIER_FP8):
        raise ValueError(
            f"HOROVOD_KV_PAGE_WIRE must be '{TIER_F32}' or '{TIER_FP8}', "
            f"got {tier!r}")
    return tier


@dataclasses.dataclass
class WirePages:
    """Decoded page payload, ready for :func:`import_pages`."""

    tier: str
    length: int                    # tokens covered (full pages + tail)
    page_size: int
    dtype: str                     # pool dtype of the f32 tier / tail
    # f32 tier: [L, full, page_size, H, D] in the pool dtype.
    k_pages: Optional[np.ndarray] = None
    v_pages: Optional[np.ndarray] = None
    # fp8 tier: e4m3 pages + one f32 scale per (layer, page, offset) row.
    kq: Optional[np.ndarray] = None
    vq: Optional[np.ndarray] = None
    kscale: Optional[np.ndarray] = None
    vscale: Optional[np.ndarray] = None
    # Partial tail page, always the pool dtype: [L, tail, H, D].
    k_tail: Optional[np.ndarray] = None
    v_tail: Optional[np.ndarray] = None

    @property
    def full_pages(self) -> int:
        return self.length // self.page_size

    @property
    def tail_tokens(self) -> int:
        return self.length - self.full_pages * self.page_size


def _quantize_full_pages(pages: np.ndarray):
    """PR 14 cold-page codec over ``[L, n, ps, H, D]`` -- the SAME
    reshape and reduction axis as ``kvcache._quantize_pages``, so wire
    quantization of a page is bitwise what ``demote_page`` would have
    produced for the identical resident bytes."""
    l, n, pg, hh, dd = pages.shape
    q, s = fp8_quantize(jnp.asarray(pages).reshape(l * n * pg, hh * dd),
                        axis=0)
    return (np.asarray(q).reshape(l, n, pg, hh, dd),
            np.asarray(s).reshape(l, n, pg))


def encode_kv(k_layers, v_layers, *, page_size: int,
              tier: Optional[str] = None) -> bytes:
    """Serialize a prompt's post-RoPE K/V (``[L, T, H, D]``, the
    ``prefill_forward`` per-sequence output) into one framed payload of
    ``T // page_size`` full pages plus an f32 tail."""
    tier = tier or wire_tier()
    if tier not in (TIER_F32, TIER_FP8):
        raise ValueError(f"unknown KV wire tier {tier!r}")
    k = np.asarray(k_layers)
    v = np.asarray(v_layers)
    if k.shape != v.shape or k.ndim != 4:
        raise ValueError(
            f"expected matching [L, T, H, D] K/V, got {k.shape} "
            f"vs {v.shape}")
    layers, length, heads, hd = k.shape
    if length < 1:
        raise ValueError("cannot encode an empty context")
    full = length // page_size
    tail = length - full * page_size
    kp = k[:, :full * page_size].reshape(layers, full, page_size,
                                         heads, hd)
    vp = v[:, :full * page_size].reshape(layers, full, page_size,
                                         heads, hd)
    chunks = []
    if full:
        if tier == TIER_FP8:
            kq, ks = _quantize_full_pages(kp)
            vq, vs = _quantize_full_pages(vp)
            chunks += [kq.tobytes(), vq.tobytes(),
                       ks.astype(np.float32).tobytes(),
                       vs.astype(np.float32).tobytes()]
        else:
            chunks += [kp.tobytes(), vp.tobytes()]
    if tail:
        chunks += [k[:, full * page_size:].tobytes(),
                   v[:, full * page_size:].tobytes()]
    payload = b"".join(chunks)
    header = json.dumps({
        "tier": tier, "layers": layers, "kv_heads": heads,
        "head_dim": hd, "page_size": page_size, "length": length,
        "dtype": str(k.dtype), "payload_bytes": len(payload),
        "sha256": hashlib.sha256(payload).hexdigest(),
    }, sort_keys=True).encode()
    return _FRAME.pack(MAGIC, WIRE_VERSION, len(header)) + header + payload


def decode_kv(buf: bytes) -> WirePages:
    """Parse and validate one framed payload; every malformation is a
    ``ValueError`` (version mismatch, truncation, hash mismatch) so the
    import path can never attach garbage pages."""
    if len(buf) < _FRAME.size:
        raise ValueError(
            f"truncated KV-page payload: {len(buf)} byte(s) is shorter "
            f"than the {_FRAME.size}-byte frame")
    magic, version, hlen = _FRAME.unpack_from(buf)
    if magic != MAGIC:
        raise ValueError(
            f"not a KV-page wire payload (magic {magic!r})")
    if version != WIRE_VERSION:
        raise ValueError(
            f"KV wire version mismatch: payload v{version}, this codec "
            f"speaks v{WIRE_VERSION} -- refusing a cross-version import")
    if len(buf) < _FRAME.size + hlen:
        raise ValueError(
            "truncated KV-page payload: header cut short")
    try:
        hdr = json.loads(buf[_FRAME.size:_FRAME.size + hlen])
    except ValueError as e:
        raise ValueError(f"corrupt KV wire header: {e}") from e
    payload = buf[_FRAME.size + hlen:]
    want = int(hdr["payload_bytes"])
    if len(payload) != want:
        raise ValueError(
            f"truncated KV-page payload: have {len(payload)} payload "
            f"byte(s), header promises {want}")
    digest = hashlib.sha256(payload).hexdigest()
    if digest != hdr["sha256"]:
        raise ValueError(
            "KV-page content hash mismatch: payload bytes do not match "
            "the header's sha256 (partial write or in-flight corruption)")
    tier = hdr["tier"]
    layers, heads = int(hdr["layers"]), int(hdr["kv_heads"])
    hd, ps = int(hdr["head_dim"]), int(hdr["page_size"])
    length = int(hdr["length"])
    dt = np.dtype(hdr["dtype"])
    full = length // ps
    tail = length - full * ps
    wp = WirePages(tier=tier, length=length, page_size=ps,
                   dtype=str(dt))
    off = 0

    def take(count: int, dtype, shape):
        nonlocal off
        nbytes = count * dtype.itemsize
        arr = np.frombuffer(payload, dtype, count=count,
                            offset=off).reshape(shape)
        off += nbytes
        return arr

    page_elems = layers * full * ps * heads * hd
    if full:
        if tier == TIER_FP8:
            pshape = (layers, full, ps, heads, hd)
            wp.kq = take(page_elems, _FP8_DTYPE, pshape)
            wp.vq = take(page_elems, _FP8_DTYPE, pshape)
            wp.kscale = take(layers * full * ps, np.dtype(np.float32),
                             (layers, full, ps))
            wp.vscale = take(layers * full * ps, np.dtype(np.float32),
                             (layers, full, ps))
        else:
            pshape = (layers, full, ps, heads, hd)
            wp.k_pages = take(page_elems, dt, pshape)
            wp.v_pages = take(page_elems, dt, pshape)
    if tail:
        tshape = (layers, tail, heads, hd)
        wp.k_tail = take(layers * tail * heads * hd, dt, tshape)
        wp.v_tail = take(layers * tail * heads * hd, dt, tshape)
    return wp


def import_pages(cache, slot: int, wp: WirePages) -> int:
    """Land a decoded payload in an empty slot of ``cache``: full pages
    are adopted into the pool (f32 or the e4m3 cold pool) and mapped in
    through :meth:`~.kvcache.PagedKVCache.attach_pages` -- the same
    entry point the prefix-cache hit path uses -- then the partial tail
    is scattered via ``write_prefill``.  Returns the number of full
    pages streamed in.  The slot ends with ``lengths[slot] ==
    wp.length`` and every page held at refcount 1 by the slot."""
    c = cache.config
    if wp.page_size != c.page_size:
        raise ValueError(
            f"wire page_size {wp.page_size} != pool page_size "
            f"{c.page_size}")
    if wp.tier == TIER_FP8 and not cache.compress:
        raise ValueError(
            "fp8 wire tier needs a compress=True decode-side cache "
            "(HOROVOD_KV_COMPRESS)")
    entries: List[Tuple[str, int]] = []
    if wp.full_pages:
        if wp.tier == TIER_FP8:
            entries = cache.adopt_compressed_pages(
                wp.kq, wp.vq, wp.kscale, wp.vscale)
        else:
            entries = cache.adopt_pages(wp.k_pages, wp.v_pages)
        cache.attach_pages(slot, entries, wp.full_pages * c.page_size)
        # attach_pages took the slot's own reference; drop the
        # importer's so the slot is the sole holder (free_slot later
        # returns the page to the pool, the leak-gate invariant).
        for kind, pid in entries:
            cache.drop_page_ref(pid, kind)
    if wp.tail_tokens:
        cache.write_prefill(slot, wp.k_tail, wp.v_tail,
                            start=wp.full_pages * c.page_size)
    return len(entries)
