"""Functional Llama prefill / tensor-parallel incremental decode.

:class:`~horovod_tpu.models.transformer.LlamaLM` is a flax module built
for training; serving needs the SAME math refactored into two functional
entry points that thread a paged KV cache instead of re-reading the whole
context every token:

* :func:`prefill_forward` -- full-context forward over a prompt that also
  returns the per-layer post-RoPE K/V ready to scatter into the cache
  (replicated; prompt work is compute-bound and tiny next to decode).
* :func:`build_decode_step` -- a single-token batched decode step
  compiled as ``jit(shard_map(...))`` over a named ``tp`` mesh.  Head
  projections are column-parallel, the ``wo``/``w_down`` closures
  row-parallel via :func:`horovod_tpu.parallel.tp.row_parallel`, so every
  activation collective is a ``collectives.ops.allreduce`` -- visible to
  the fusion planner, registered with the span recorder at trace time
  (:func:`~horovod_tpu.timeline.spans.note_leg`), and priced by the
  static auditor through the ``_meta`` dict the returned wrapper carries
  (the ``_InstrumentedStep`` convention).

Every cast mirrors ``models/transformer.py`` operation-for-operation
(``Dense`` computes ``x.astype(dtype) @ kernel.astype(dtype)``, RMSNorm
normalizes in f32, RoPE rotates in f32, the tied-embedding readout runs
in f32), so incremental decode matches the flax full-context forward to
float tolerance -- the tentpole parity contract.

Multi-LoRA: ``stack_adapters`` packs N trained adapter trees into banked
``[n_adapters, ...]`` leaves; the decode step then gathers each slot's
adapter pair by a per-slot ``adapter_ids`` operand INSIDE the step, so
one base model serves heterogeneous adapters in one decode batch
(tensor-parallel meshes decline the banks -- adapters stay tp=1).

Shared read-only pages (PR 16): the decode step never sees page
ownership -- it reads K/V through the slot's ``page_table`` row and
masks positions at or beyond ``lengths[slot]``, so two slots whose
table rows point at the SAME physical page (a radix prefix-cache hit)
compute bitwise-identical attention to two slots holding private
copies: identical bytes in, identical gather/mask/matmul, identical
logits out.  Isolation is therefore the cache's contract, not the
step's: decode writes always scatter at ``lengths[slot]`` (past any
shared prefix, which is page-aligned and shorter than the prompt), and
any write that WOULD land inside a shared page is preceded by a
copy-on-write clone in ``PagedKVCache.reserve(..., writable_from=)``.
The shared-page bitwise proof lives next to the eviction/reuse proof in
``test_slot_eviction_reuse_no_stale_attention_mass``.
"""

from __future__ import annotations

import time as _time
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..models.transformer import LlamaConfig, rotary_embedding
from ..ops.attention import (decode_attention, flash_attention,
                             verify_attention)
from ..parallel.tp import row_parallel
from ..timeline import spans as _spans

TP_AXIS = "tp"

_COLUMN_KEYS = ("wq", "wk", "wv", "w_gate", "w_up")
_ROW_KEYS = ("wo", "w_down")


# ---------------------------------------------------------------------------
# Shared math (the Dense/RMSNorm mirror).
# ---------------------------------------------------------------------------


def _dense(x, node, dtype, *, lora_select=None, lora_alpha=16.0):
    """``Dense.__call__`` replayed over a raw param node.

    ``lora_select``: optional ``(a, b)`` adapter pair already gathered
    for this call -- either a plain ``[d_in, r]/[r, d_out]`` pair (one
    adapter) or per-slot ``[s, d_in, r]/[s, r, d_out]`` banks.
    """
    y = x.astype(dtype) @ node["kernel"].astype(dtype)
    if lora_select is not None:
        a, b = lora_select
        r = a.shape[-1]
        scale = jnp.asarray(lora_alpha / r, dtype)
        if a.ndim == 2:
            y = y + (x.astype(dtype) @ a.astype(dtype)
                     @ b.astype(dtype)) * scale
        else:
            # Per-slot banks: slot s uses its own (a[s], b[s]).
            t = jnp.einsum("sqd,sdr->sqr", x.astype(dtype),
                           a.astype(dtype))
            y = y + jnp.einsum("sqr,sro->sqo", t, b.astype(dtype)) * scale
    return y


def _rmsnorm(x, scale, dtype, epsilon: float = 1e-5):
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + epsilon)
    return (norm * scale).astype(dtype)


def _node_lora(node, adapters_node, select):
    """Resolve the adapter pair for one Dense node, preferring banked
    adapters (``adapters_node``) gathered by ``select`` over in-tree
    ``lora_a``/``lora_b`` leaves."""
    if adapters_node is not None:
        return select(adapters_node["lora_a"], adapters_node["lora_b"])
    if "lora_a" in node:
        return node["lora_a"], node["lora_b"]
    return None


# ---------------------------------------------------------------------------
# Prefill: full-context forward exposing per-layer K/V.
# ---------------------------------------------------------------------------


def prefill_forward(params, config: LlamaConfig, tokens, positions=None,
                    *, segment_ids=None, dtype=jnp.float32,
                    adapters=None, adapter_id=None, lora_alpha=16.0,
                    past=None) -> Tuple[Any, Any, Any]:
    """Forward a prompt batch, returning ``(logits, k_layers, v_layers)``.

    ``tokens``: ``[b, t]`` int32.  ``k_layers``/``v_layers``:
    ``[num_layers, b, t, num_kv_heads, head_dim]`` post-RoPE -- the
    layout :meth:`PagedKVCache.write_prefill` scatters (squeeze the batch
    dim for the per-slot write).  Padding isolation via ``segment_ids``
    follows the model convention (pad tokens get segment 0).

    ``adapters``/``adapter_id``: banked LoRA tree + the ONE adapter this
    prompt uses (prefill admits one request at a time).

    ``past``: chunked prefill continuation -- a ``(k_layers, v_layers)``
    pair from the previous chunks (``[num_layers, b, t_past, kv_heads,
    head_dim]`` each).  ``tokens`` is then the CURRENT chunk only; its
    queries attend over ``past ++ chunk`` keys with the bottom-right
    aligned causal mask (exactly the KV-cache convention
    :func:`~horovod_tpu.ops.attention.flash_attention` implements for
    ``tq < tk``), and the returned K/V cover the FULL context so the
    caller chains chunks by simple replacement.  ``positions`` must be
    the chunk's absolute offsets (``t_past .. t_past + t``); the chunk
    logits equal the same rows of a whole-prompt forward to float
    tolerance (the chunked-prefill parity contract).
    """
    cfg = config
    p = params["params"] if "params" in params else params
    b, t = tokens.shape
    t_past = 0 if past is None else int(past[0].shape[2])
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(t_past, t_past + t),
                                     (b, t))
    if past is not None and segment_ids is not None:
        raise NotImplementedError(
            "chunked prefill with segment_ids: pad isolation across "
            "the past/chunk seam is not modeled; chunk unpadded prompts")
    emb = p["tok_embed"]
    x = emb[tokens].astype(dtype)

    def select(a, bnk):
        return a[adapter_id], bnk[adapter_id]

    ad = (adapters["params"] if adapters is not None and
          "params" in adapters else adapters)
    ks, vs = [], []
    for li in range(cfg.num_layers):
        blk = p[f"layer_{li}"]
        abk = None if ad is None else ad.get(f"layer_{li}")

        def lora(group, name, _blk=blk, _abk=abk):
            node = _blk[group][name]
            anode = None if _abk is None else _abk.get(group, {}).get(name)
            return _node_lora(node, anode, select)

        h = _rmsnorm(x, blk["attn_norm"]["scale"], dtype)
        attn = blk["attn"]
        q = _dense(h, attn["wq"], dtype, lora_select=lora("attn", "wq"),
                   lora_alpha=lora_alpha)
        k = _dense(h, attn["wk"], dtype, lora_select=lora("attn", "wk"),
                   lora_alpha=lora_alpha)
        v = _dense(h, attn["wv"], dtype, lora_select=lora("attn", "wv"),
                   lora_alpha=lora_alpha)
        q = q.reshape(b, t, cfg.num_heads, cfg.head_dim).transpose(
            0, 2, 1, 3)
        k = k.reshape(b, t, cfg.num_kv_heads, cfg.head_dim).transpose(
            0, 2, 1, 3)
        v = v.reshape(b, t, cfg.num_kv_heads, cfg.head_dim).transpose(
            0, 2, 1, 3)
        q = rotary_embedding(q, positions, cfg.rope_theta)
        k = rotary_embedding(k, positions, cfg.rope_theta)
        if past is not None:
            # Chunk continuation: this chunk's queries see every past
            # key; the bottom-right aligned causal mask handles the
            # within-chunk triangle.  past k/v arrive in cache layout
            # [b, t_past, H, D] -- move time back to the attention axis.
            k_full = jnp.concatenate(
                [past[0][li].transpose(0, 2, 1, 3).astype(k.dtype), k],
                axis=2)
            v_full = jnp.concatenate(
                [past[1][li].transpose(0, 2, 1, 3).astype(v.dtype), v],
                axis=2)
        else:
            k_full, v_full = k, v
        o = flash_attention(q, k_full, v_full, causal=True,
                            segment_ids=segment_ids)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, -1)
        x = x + _dense(o, attn["wo"], dtype, lora_select=lora("attn", "wo"),
                       lora_alpha=lora_alpha)
        # Cache layout: [b, t, kv_heads, head_dim], post-RoPE -- the
        # FULL context (past ++ chunk) so chunk callers chain by
        # replacement.
        ks.append(k_full.transpose(0, 2, 1, 3))
        vs.append(v_full.transpose(0, 2, 1, 3))

        h = _rmsnorm(x, blk["mlp_norm"]["scale"], dtype)
        mlp = blk["mlp"]
        gate = _dense(h, mlp["w_gate"], dtype,
                      lora_select=lora("mlp", "w_gate"),
                      lora_alpha=lora_alpha)
        up = _dense(h, mlp["w_up"], dtype,
                    lora_select=lora("mlp", "w_up"),
                    lora_alpha=lora_alpha)
        x = x + _dense(jax.nn.silu(gate) * up, mlp["w_down"], dtype,
                       lora_select=lora("mlp", "w_down"),
                       lora_alpha=lora_alpha)

    x = _rmsnorm(x, p["final_norm"]["scale"], dtype)
    logits = x.astype(jnp.float32) @ emb.astype(jnp.float32).T
    return logits, jnp.stack(ks), jnp.stack(vs)


# ---------------------------------------------------------------------------
# Tensor-parallel decode step.
# ---------------------------------------------------------------------------


def decode_param_specs(params, tp_axis: str = TP_AXIS):
    """PartitionSpec tree for ``shard_map`` over the decode params:
    column kernels split on the output dim, row kernels on the input dim,
    everything else replicated (the ``shard_tp_params`` key convention)."""

    def spec(path, leaf):
        names = [getattr(kk, "key", "") for kk in path]
        if "kernel" in names and leaf.ndim == 2:
            owner = names[-2] if names[-1] == "kernel" else ""
            if owner in _COLUMN_KEYS:
                return P(None, tp_axis)
            if owner in _ROW_KEYS:
                return P(tp_axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


class ServingDecodeStep:
    """Callable wrapper around the jitted decode step.

    Carries the builder ``_meta`` the static auditor dispatches on (the
    ``_InstrumentedStep`` convention: ``analysis.meta_from_step`` reads
    ``_meta``, ``audit_step`` unwraps ``_fn``) and times each dispatch
    into the span recorder under its leg (``serving_decode`` for the
    one-token step, ``serving_verify`` for the speculative verify step).
    """

    def __init__(self, fn, meta: dict, leg: str = "serving_decode"):
        self._fn = fn
        self._meta = meta
        self._leg = leg

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __call__(self, *args):
        rec = _spans.recorder()
        with rec.span("dispatch", name="serving", leg=self._leg):
            return self._fn(*args)


def build_decode_step(config: LlamaConfig, mesh, *,
                      slots: int, page_size: int, pages_per_slot: int,
                      dtype=jnp.float32, with_lora: bool = False,
                      lora_alpha: float = 16.0,
                      tp_axis: str = TP_AXIS, width: int = 1,
                      compress: bool = False) -> ServingDecodeStep:
    """Compile the batched decode (or width-k verify) step over ``mesh``.

    Signature of the returned step (``width == 1``)::

        logits, k_pool, v_pool = step(params, k_pool, v_pool, tokens,
                                      positions, page_table, active
                                      [, kq, vq, kscale, vscale,
                                         ctable, cmask]
                                      [, adapters, adapter_ids])

    ``tokens``/``positions``/``active``: ``[slots]`` (current token, its
    absolute position == live length before this step, slot liveness).
    ``page_table``: ``[slots, pages_per_slot]``.  The step writes the new
    token's post-RoPE K/V into its page in-step, attends over the
    length-masked slot view, and returns replicated next-token logits.
    Idle slots produce zero attention output (dead-row convention) and
    their logits are discarded by the engine.

    ``width > 1`` is the speculative-decoding VERIFY step (built through
    :func:`build_verify_step`): ``tokens`` widens to ``[slots, width]``
    (the last sampled token followed by ``width - 1`` drafts), every
    column's K/V is scattered to its own (page, offset) in-step, and
    attention runs :func:`~horovod_tpu.ops.attention.verify_attention`
    -- the same paged gather, with the length mask extended one key per
    draft column.  Logits come back ``[slots, width, vocab]``, target
    argmaxes for ALL width positions from ONE dispatch.  Columns past a
    slot's accepted prefix leave garbage K/V above the rolled-back
    length -- unreachable by the masking contract, exactly like a
    recycled page.

    ``compress=True`` (the fp8 KV-cache path) appends the six e4m3 pool
    operands from :meth:`PagedKVCache.compress_operands`; gathers blend
    dequantised cold pages in wherever ``cmask`` is set.  Purely local
    indexing/dequant -- the collective contract is unchanged.
    """
    cfg = config
    tp = int(np.prod([mesh.shape[a] for a in mesh.axis_names
                      if a == tp_axis])) if mesh is not None else 1
    if mesh is not None and tp_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {tp_axis!r} axis: {mesh.axis_names}")
    for what, n in (("num_heads", cfg.num_heads),
                    ("num_kv_heads", cfg.num_kv_heads),
                    ("ffn_hidden", cfg.ffn_hidden)):
        if n % tp:
            raise ValueError(f"{what}={n} not divisible by tp={tp}")
    if with_lora and tp > 1:
        raise NotImplementedError(
            "per-slot LoRA banks are tp=1 only (a row-parallel adapter "
            "would need its own psum fold); shard requests, not adapters")
    if width < 1:
        raise ValueError(f"width must be >= 1, got {width}")
    if with_lora and width > 1:
        raise NotImplementedError(
            "speculative verify with per-slot LoRA banks is not wired; "
            "serve adapters with plain decode")
    heads_l = cfg.num_heads // tp
    kvh_l = cfg.num_kv_heads // tp
    hd = cfg.head_dim
    kind = "serving_decode" if width == 1 else "serving_verify"
    # Per-layer TP psum rows come from the shared exchange-plan IR
    # (planned once, rendered verbatim by spans/auditor): legs[2*li] is
    # layer li's attn_wo psum, legs[2*li + 1] its mlp_down psum.
    from ..controller import fusion as _fusion
    splan = _fusion.plan_exchange(
        "serving", kind=kind, layers=cfg.num_layers, slots=slots,
        width=width, d_model=cfg.d_model, dtype=str(jnp.dtype(dtype)),
        axis=tp_axis)
    # Register the plan rows at BUILD time, not trace time: plan-
    # fingerprint executable sharing means an identical step may never
    # re-trace, but each built step still owns its legs in the span
    # registry (one registration per build, like one per trace before).
    for _leg in splan.legs:
        _spans.note_leg(_leg, bucket_id=_leg.bucket)
    max_len = pages_per_slot * page_size

    def spmd(params, k_pool, v_pool, tokens, positions, page_table,
             active, *extra):
        if compress:
            kq_pool, vq_pool, kscale, vscale, ctable, cmask = extra[:6]
            extra = extra[6:]
        adapters, adapter_ids = extra if extra else (None, None)
        p = params["params"] if "params" in params else params
        ad = (adapters["params"] if adapters is not None and
              "params" in adapters else adapters)
        s = tokens.shape[0]
        emb = p["tok_embed"]
        scratch = slots * pages_per_slot
        if width == 1:
            x = emb[tokens].astype(dtype)[:, None, :]      # [S, 1, d]
            pos2 = positions[:, None]                      # [S, 1]
            # The step writes EVERY slot's K/V (fixed batch shape); idle
            # slots are redirected to the pool's trailing scratch page
            # so they never clobber a live page.
            page = jnp.where(
                active,
                page_table[jnp.arange(s), positions // page_size],
                scratch)
            off = positions % page_size
        else:
            x = emb[tokens].astype(dtype)                  # [S, W, d]
            pos2 = positions[:, None] + jnp.arange(width)[None, :]
            # Columns may run past max_len on a nearly-full slot (the
            # host caps emission); redirect those writes to scratch too.
            writable = active[:, None] & (pos2 < max_len)
            idx = jnp.clip(pos2 // page_size, 0, pages_per_slot - 1)
            page = jnp.where(
                writable,
                jnp.take_along_axis(page_table, idx, axis=1), scratch)
            off = pos2 % page_size

        def gather_view(li, pool, qpool=None, scale=None):
            view = pool[li][page_table]     # [S, pps, page, kvh_l, hd]
            if compress:
                deq = (qpool[li][ctable].astype(jnp.float32)
                       * scale[li][ctable][..., None, None]
                       ).astype(view.dtype)
                view = jnp.where(cmask[..., None, None, None], deq, view)
            return view.reshape(
                s, pages_per_slot * page_size, kvh_l, hd
            ).transpose(0, 2, 1, 3)

        def select(a, b):
            return a[adapter_ids], b[adapter_ids]

        for li in range(cfg.num_layers):
            blk = p[f"layer_{li}"]
            abk = None if ad is None else ad.get(f"layer_{li}")

            def lora(group, name, _blk=blk, _abk=abk):
                node = _blk[group][name]
                anode = (None if _abk is None
                         else _abk.get(group, {}).get(name))
                return _node_lora(node, anode, select)

            h = _rmsnorm(x, blk["attn_norm"]["scale"], dtype)
            attn = blk["attn"]
            q = _dense(h, attn["wq"], dtype,
                       lora_select=lora("attn", "wq"),
                       lora_alpha=lora_alpha)
            k = _dense(h, attn["wk"], dtype,
                       lora_select=lora("attn", "wk"),
                       lora_alpha=lora_alpha)
            v = _dense(h, attn["wv"], dtype,
                       lora_select=lora("attn", "wv"),
                       lora_alpha=lora_alpha)
            q = q.reshape(s, width, heads_l, hd).transpose(0, 2, 1, 3)
            k = k.reshape(s, width, kvh_l, hd).transpose(0, 2, 1, 3)
            v = v.reshape(s, width, kvh_l, hd).transpose(0, 2, 1, 3)
            q = rotary_embedding(q, pos2, cfg.rope_theta)
            k = rotary_embedding(k, pos2, cfg.rope_theta)

            # In-step cache write: each column's K/V lands at its
            # (page, offset) -- one scatter per pool per layer.
            pool_dt = k_pool.dtype
            if width == 1:
                k_pool = k_pool.at[li, page, off].set(
                    k[:, :, 0, :].astype(pool_dt))
                v_pool = v_pool.at[li, page, off].set(
                    v[:, :, 0, :].astype(pool_dt))
            else:
                k_pool = k_pool.at[li, page, off].set(
                    k.transpose(0, 2, 1, 3).astype(pool_dt))
                v_pool = v_pool.at[li, page, off].set(
                    v.transpose(0, 2, 1, 3).astype(pool_dt))

            # Slot view: gather this slot's pages -> [S, kvh, max_len, d]
            # (cold pages dequantised from the e4m3 pool when present).
            if compress:
                ks = gather_view(li, k_pool, kq_pool, kscale)
                vs = gather_view(li, v_pool, vq_pool, vscale)
            else:
                ks = gather_view(li, k_pool)
                vs = gather_view(li, v_pool)
            lengths = jnp.where(active, positions + 1, 0)
            if width == 1:
                o = decode_attention(q.astype(dtype), ks.astype(dtype),
                                     vs.astype(dtype), lengths=lengths)
            else:
                o = verify_attention(q.astype(dtype), ks.astype(dtype),
                                     vs.astype(dtype), lengths=lengths)
            o = o.transpose(0, 2, 1, 3).reshape(s, width, heads_l * hd)

            # Row-parallel closures: the activation allreduce routes
            # through collectives.ops (planner/auditor/span visible).
            y = row_parallel(o.astype(dtype),
                             attn["wo"]["kernel"].astype(dtype),
                             axis=tp_axis)
            wo_lora = lora("attn", "wo")
            if wo_lora is not None:
                y = y + _dense_lora_only(o, wo_lora, dtype, lora_alpha)
            x = x + y

            h = _rmsnorm(x, blk["mlp_norm"]["scale"], dtype)
            mlp = blk["mlp"]
            gate = _dense(h, mlp["w_gate"], dtype,
                          lora_select=lora("mlp", "w_gate"),
                          lora_alpha=lora_alpha)
            up = _dense(h, mlp["w_up"], dtype,
                        lora_select=lora("mlp", "w_up"),
                        lora_alpha=lora_alpha)
            act = (jax.nn.silu(gate) * up).astype(dtype)
            y = row_parallel(act, mlp["w_down"]["kernel"].astype(dtype),
                             axis=tp_axis)
            wd_lora = lora("mlp", "w_down")
            if wd_lora is not None:
                y = y + _dense_lora_only(act, wd_lora, dtype, lora_alpha)
            x = x + y

        x = _rmsnorm(x, p["final_norm"]["scale"], dtype)
        logits = x.astype(jnp.float32) @ emb.astype(jnp.float32).T
        if width == 1:
            logits = logits[:, 0, :]                       # [S, vocab]
        return logits, k_pool, v_pool

    n_base = 7 + (6 if compress else 0)

    def _build(params_example, adapters_example=None):
        pool_spec = P(None, None, None, tp_axis, None)
        in_specs = [decode_param_specs(params_example, tp_axis),
                    pool_spec, pool_spec, P(), P(), P(), P()]
        if compress:
            # e4m3 pools shard like the f32 pools; scales/table/mask
            # are replicated host metadata.
            in_specs += [pool_spec, pool_spec, P(), P(), P(), P()]
        if adapters_example is not None:
            in_specs += [jax.tree.map(lambda _: P(), adapters_example),
                         P()]
        fn = jax.shard_map(spmd, mesh=mesh, in_specs=tuple(in_specs),
                           out_specs=(P(), pool_spec, pool_spec),
                           check_vma=False)
        return jax.jit(fn)

    # The jitted callable is built lazily on first call so the shard_map
    # in_specs can mirror the actual params tree (LoRA leaves included).
    # Memoized in the session ExecutableCache by the plan fingerprint:
    # serving steps sharing exchange structure (same config/slots/width)
    # on the same mesh share one compiled executable.
    def step(*args):
        # The fingerprint keys the exchange structure; the extras pin
        # the non-exchange statics (page geometry, arg arity, mesh) the
        # compiled program also depends on.
        fn = _fusion.plan_executable(
            splan,
            lambda: _build(args[0],
                           args[n_base] if len(args) > n_base else None),
            extra=(len(args), bool(compress), int(page_size),
                   int(pages_per_slot), mesh))
        return fn(*args)

    meta = {"kind": kind, "world": tp, "tp": tp,
            "num_layers": cfg.num_layers, "d_model": cfg.d_model,
            "slots": int(slots), "dtype": str(jnp.dtype(dtype)),
            "lora": bool(with_lora), "compress": bool(compress)}
    if width > 1:
        meta["width"] = int(width)
    return ServingDecodeStep(step, meta, leg=kind)


def build_verify_step(config: LlamaConfig, mesh, *,
                      slots: int, width: int, page_size: int,
                      pages_per_slot: int, dtype=jnp.float32,
                      tp_axis: str = TP_AXIS,
                      compress: bool = False) -> ServingDecodeStep:
    """Compile the speculative-decoding verify step: one fixed-shape
    dispatch scoring ``width`` tokens per slot (the last sampled token
    plus ``width - 1`` drafter proposals).

    A width-k generalisation of :func:`build_decode_step` -- same paged
    scatter, same length-masked attention (one extra visible key per
    draft column), same two row-parallel psums per layer, just ``width``
    times as wide (``slots * width * d_model`` elements; the widened
    contract the static auditor prices under ``kind=serving_verify``).
    The engine accepts each slot's longest draft prefix agreeing with
    the returned argmaxes, plus the target's own token at the first
    disagreement -- greedy-exact by construction.
    """
    if width < 2:
        raise ValueError(
            f"verify step needs width >= 2 (got {width}); width 1 is "
            "plain decode -- use build_decode_step")
    return build_decode_step(
        config, mesh, slots=slots, page_size=page_size,
        pages_per_slot=pages_per_slot, dtype=dtype, tp_axis=tp_axis,
        width=width, compress=compress)


def _dense_lora_only(x, lora_select, dtype, lora_alpha):
    """The adapter half of ``_dense`` (added after a row-parallel psum;
    tp=1 only, enforced by the builder)."""
    a, b = lora_select
    r = a.shape[-1]
    scale = jnp.asarray(lora_alpha / r, dtype)
    if a.ndim == 2:
        return (x.astype(dtype) @ a.astype(dtype)
                @ b.astype(dtype)) * scale
    t = jnp.einsum("sqd,sdr->sqr", x.astype(dtype), a.astype(dtype))
    return jnp.einsum("sqr,sro->sqo", t, b.astype(dtype)) * scale


def greedy_sample(logits) -> jnp.ndarray:
    """Deterministic next token per slot: argmax over the vocab."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Multi-LoRA banks.
# ---------------------------------------------------------------------------


def stack_adapters(param_trees) -> Any:
    """Pack N per-adapter param trees into one banked adapter tree.

    Input trees are full model params (each holding ``lora_a``/``lora_b``
    leaves, e.g. from ``LlamaLM(lora_rank=r).init``); the result keeps
    ONLY the adapter leaves, stacked on a new leading ``n_adapters`` dim,
    nested exactly like the source tree -- the layout the decode step's
    per-slot ``adapter_ids`` gather consumes.
    """
    if not param_trees:
        raise ValueError("need at least one adapter tree")

    def keep(tree):
        if not isinstance(tree, dict):
            return None
        out = {}
        for kk, vv in tree.items():
            if kk in ("lora_a", "lora_b"):
                out[kk] = vv
            else:
                sub = keep(vv)
                if sub:
                    out[kk] = sub
        return out

    kept = [keep(t) for t in param_trees]
    if not kept[0]:
        raise ValueError("adapter trees hold no lora_a/lora_b leaves")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *kept)
