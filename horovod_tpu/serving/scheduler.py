"""Continuous-batching request scheduler.

The decode step has a FIXED batch shape (``slots`` sequences), so
throughput is a slot-occupancy game: the scheduler admits queued
requests into free slots the moment one opens (no generation-boundary
barriers -- "continuous" batching), recycles a slot the instant its
request finishes, and evicts nothing by default (admission is gated on
KV page availability via :meth:`PagedKVCache.can_admit`, so an admitted
request can always run to completion).

Lifecycle: ``queued -> prefill -> decode -> done``, with a ``draining``
detour used by the elastic control plane: a draining slot stops
admitting follow-on work and its request either runs to completion
(``completed``) or is ``suspended`` -- popped off the batch with its KV
pages freed -- to be restored and re-prefilled on the post-resize
mesh.  Disaggregated serving (PR 20) adds a ``handoff`` stop between
``prefill`` and ``decode``: the prompt's K/V was computed on a REMOTE
prefill worker and its pages are still in flight over the rendezvous
KV plane, so the slot holds a request that cannot decode yet -- the
fleet router and control plane must not count it as decoding capacity.
Every transition is instrumented through the PR 6
:class:`MetricsRegistry` --

* ``horovod_serving_requests_total{event}`` -- submitted / admitted /
  completed / rejected / draining / suspended / reprefill transitions,
* ``horovod_serving_tokens_total{phase}`` -- prefill vs decode tokens,
* ``horovod_serving_queue_depth`` / ``horovod_serving_batch_occupancy``
  gauges plus ``horovod_serving_slot_states{state}`` (active / handoff
  / draining / free slot counts, so dashboards can tell a draining
  batch from an idle one and a pages-in-flight slot from a decoding
  one),
* ``horovod_serving_spec_tokens_total{outcome}`` -- speculative-decoding
  draft tokens proposed vs accepted (acceptance rate =
  accepted / proposed),
* ``horovod_serving_ttft_seconds`` / ``horovod_serving_token_latency_seconds``
  histograms (time-to-first-token, per-output-token latency),
* per-tenant SLO families (PR 16):
  ``horovod_serving_ttft_by_tenant_seconds{tenant}``,
  ``horovod_serving_tenant_occupancy{tenant}``,
  ``horovod_serving_tenant_queue_depth{tenant}``

-- the same families the bench serving block and ``serving_probe``
scrape back out of ``/metrics``.

Multi-tenancy (PR 16): :class:`TenantClass` declares per-class weight,
TTFT SLO budget and slot-share cap; admission becomes stride scheduling
over per-tenant FIFO heads (weighted fair service, no class starves,
an adversarial flood is capped at its ``max_share`` of the batch).
With no classes configured the scheduler is the original single-tenant
strict-FIFO, unchanged.
"""

from __future__ import annotations

import collections
import dataclasses
import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..timeline.metrics import registry as _registry

# Per-token decode latencies sit well under the default step buckets'
# sweet spot; extend the low end so p50 lands inside a bucket.
LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                   0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


@dataclasses.dataclass(frozen=True)
class TenantClass:
    """One SLO class in the multi-tenant scheduler.

    ``weight`` drives stride-scheduled admission (a tenant's share of
    admitted prefill+decode work is proportional to its weight under
    contention); ``max_share`` caps the fraction of decode slots the
    tenant may hold while OTHER tenants are queued (an adversarial
    flood cannot starve the batch); ``ttft_slo_s`` is the class's TTFT
    p99 budget -- the fairness gate the BENCH_r17 drill asserts."""

    name: str
    weight: float = 1.0
    ttft_slo_s: float = 1.0
    max_share: float = 1.0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name}: weight must be > 0")
        if not 0.0 < self.max_share <= 1.0:
            raise ValueError(
                f"tenant {self.name}: max_share must be in (0, 1]")


def parse_tenant_classes(spec: str) -> Dict[str, TenantClass]:
    """``"name:weight[:ttft_slo_s[:max_share]],..."`` -> class map
    (the ``HOROVOD_TENANT_CLASSES`` wire format)."""
    out: Dict[str, TenantClass] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split(":")
        name = parts[0]
        weight = float(parts[1]) if len(parts) > 1 else 1.0
        slo = float(parts[2]) if len(parts) > 2 else 1.0
        share = float(parts[3]) if len(parts) > 3 else 1.0
        out[name] = TenantClass(name=name, weight=weight,
                                ttft_slo_s=slo, max_share=share)
    return out


@dataclasses.dataclass
class Request:
    """One inference request moving through the serving lifecycle."""

    rid: int
    prompt: np.ndarray                 # int32 [t]
    max_new_tokens: int
    adapter_id: int = 0
    arrival_s: float = 0.0             # open-loop arrival offset
    # queued|prefill|handoff|decode|draining|done
    state: str = "queued"
    slot: int = -1
    tokens: List[int] = dataclasses.field(default_factory=list)
    admit_s: Optional[float] = None
    first_token_s: Optional[float] = None
    done_s: Optional[float] = None
    token_latencies: List[float] = dataclasses.field(default_factory=list)
    tenant: str = "default"            # SLO class (TenantClass.name)
    session_id: Optional[int] = None   # multi-turn warm-KV session key
    # Load-generator engine affinity hint (per-engine arrival skew in
    # fleet traffic shapes); None = the router decides freely.
    engine_hint: Optional[int] = None

    @property
    def prompt_len(self) -> int:
        return int(len(self.prompt))

    @property
    def finished(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_s is None:
            return None
        return self.first_token_s - self.arrival_s


class ContinuousBatchScheduler:
    """Admit/evict requests into a fixed-shape decode batch."""

    def __init__(self, slots: int, cache=None, token_budget: int = 1,
                 tenants: Optional[Dict[str, TenantClass]] = None):
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        if token_budget < 1:
            raise ValueError(
                f"token_budget must be >= 1, got {token_budget}")
        self.slots = slots
        self.cache = cache
        # Worst-case tokens a slot can append in ONE step: 1 for plain
        # decode, k+1 under speculative decoding (k drafts + the
        # target's own token).  Admission must price this in or a
        # full-acceptance burst can oversubscribe KV pages mid-step.
        self.token_budget = token_budget
        self.queue: "collections.deque[Request]" = collections.deque()
        self.active: dict[int, Request] = {}
        self._free_slots = list(range(slots - 1, -1, -1))  # pop() -> 0, 1...
        self.admitting = True
        # Multi-tenant SLO classes: empty means single-tenant strict
        # FIFO (the pre-PR-16 behavior, byte for byte).  With classes,
        # admission is stride-scheduled per tenant (weighted fair) and
        # per-tenant occupancy caps apply under contention.
        self.tenants: Dict[str, TenantClass] = dict(tenants or {})
        self._tenant_pass: Dict[str, float] = {}
        self._tenants_seen = {"default"} | set(self.tenants)
        reg = _registry()
        self._m_requests = reg.counter(
            "horovod_serving_requests_total",
            "Serving request lifecycle transitions", labelnames=("event",))
        self._m_tokens = reg.counter(
            "horovod_serving_tokens_total",
            "Tokens processed by the serving engine", labelnames=("phase",))
        self._m_queue = reg.gauge(
            "horovod_serving_queue_depth", "Requests waiting for a slot")
        self._m_occ = reg.gauge(
            "horovod_serving_batch_occupancy",
            "Live fraction of the fixed decode batch (0..1)")
        self._m_ttft = reg.histogram(
            "horovod_serving_ttft_seconds", "Time to first token",
            buckets=LATENCY_BUCKETS)
        self._m_tok_lat = reg.histogram(
            "horovod_serving_token_latency_seconds",
            "Per-output-token latency", buckets=LATENCY_BUCKETS)
        self._m_slot_states = reg.gauge(
            "horovod_serving_slot_states",
            "Decode-batch slots by lifecycle state",
            labelnames=("state",))
        self._m_spec = reg.counter(
            "horovod_serving_spec_tokens_total",
            "Speculative-decoding draft tokens by outcome",
            labelnames=("outcome",))
        # Per-tenant SLO families, registered alongside the slot-state
        # gauges so the control plane's policies can read them.
        self._m_ttft_tenant = reg.histogram(
            "horovod_serving_ttft_by_tenant_seconds",
            "Time to first token per SLO class",
            buckets=LATENCY_BUCKETS, labelnames=("tenant",))
        self._m_tenant_occ = reg.gauge(
            "horovod_serving_tenant_occupancy",
            "Decode-batch slot fraction held per SLO class",
            labelnames=("tenant",))
        self._m_tenant_queue = reg.gauge(
            "horovod_serving_tenant_queue_depth",
            "Requests waiting for a slot per SLO class",
            labelnames=("tenant",))

    # -- state gauges ------------------------------------------------------
    @property
    def occupancy(self) -> float:
        return len(self.active) / self.slots

    @property
    def draining_slots(self) -> List[int]:
        return [s for s, r in self.active.items() if r.state == "draining"]

    @property
    def handoff_slots(self) -> List[int]:
        """Slots whose prompt K/V is computed but still in flight from
        a remote prefill worker (disaggregated serving)."""
        return [s for s, r in self.active.items() if r.state == "handoff"]

    def _update_gauges(self) -> None:
        self._m_queue.set(len(self.queue))
        self._m_occ.set(self.occupancy)
        draining = len(self.draining_slots)
        handoff = len(self.handoff_slots)
        self._m_slot_states.labels(state="draining").set(draining)
        self._m_slot_states.labels(state="handoff").set(handoff)
        self._m_slot_states.labels(state="active").set(
            len(self.active) - draining - handoff)
        self._m_slot_states.labels(state="free").set(len(self._free_slots))
        for tname in self._tenants_seen:
            self._m_tenant_occ.labels(tenant=tname).set(
                sum(1 for r in self.active.values()
                    if r.tenant == tname) / self.slots)
            self._m_tenant_queue.labels(tenant=tname).set(
                sum(1 for r in self.queue if r.tenant == tname))

    # -- tenant fairness ---------------------------------------------------
    def _tclass(self, name: str) -> TenantClass:
        return self.tenants.get(name) or TenantClass(name=name)

    def _pick_index(self) -> int:
        """Index into ``queue`` of the next admission candidate.

        Single-tenant: 0 -- strict FIFO, the head blocks (no
        head-of-line bypass, TTFT ordering stays honest).  With tenant
        classes: stride scheduling over each tenant's FIFO head -- the
        tenant with the lowest weight-normalized virtual pass goes
        next, skipping tenants at their ``max_share`` occupancy cap
        while others wait.  -1 when every waiting tenant is capped."""
        if not self.tenants:
            return 0
        heads: Dict[str, int] = {}
        for qi, req in enumerate(self.queue):
            if req.tenant not in heads:
                heads[req.tenant] = qi
        active_by: Dict[str, int] = {}
        for r in self.active.values():
            active_by[r.tenant] = active_by.get(r.tenant, 0) + 1
        best = None
        for tname, qi in heads.items():
            tc = self._tclass(tname)
            cap = max(1, math.ceil(tc.max_share * self.slots))
            if len(heads) > 1 and active_by.get(tname, 0) >= cap:
                continue
            key = (self._tenant_pass.get(tname, 0.0), qi)
            if best is None or key < best[0]:
                best = (key, qi)
        return -1 if best is None else best[1]

    # -- transitions -------------------------------------------------------
    def submit(self, req: Request) -> None:
        """queued: request enters the wait queue (arrival already
        happened from the load generator's point of view)."""
        if req.prompt_len < 1:
            raise ValueError(f"request {req.rid}: empty prompt")
        req.state = "queued"
        self.queue.append(req)
        if req.tenant not in self._tenants_seen:
            self._tenants_seen.add(req.tenant)
        if self.tenants and req.tenant not in self._tenant_pass:
            # A late-joining tenant starts at the current minimum pass,
            # not zero -- stride scheduling's no-catchup-monopoly rule.
            self._tenant_pass[req.tenant] = min(
                self._tenant_pass.values(), default=0.0)
        self._m_requests.labels(event="submitted").inc()
        self._update_gauges()

    def admit(self, now_s: float) -> List[Tuple[int, Request]]:
        """Move queued requests into free slots while pages allow.

        Single-tenant: FIFO, the head of the queue blocks (no
        head-of-line bypass -- keeps TTFT ordering honest under
        overload).  With tenant classes the candidate comes from
        :meth:`_pick_index` (weighted fair, occupancy-capped) and that
        CANDIDATE blocks on pages -- ordering stays honest per class.
        Returns ``(slot, request)`` pairs the engine must now prefill.
        """
        out: List[Tuple[int, Request]] = []
        if not self.admitting:
            self._update_gauges()
            return out
        while self.queue and self._free_slots:
            qi = self._pick_index()
            if qi < 0:
                break
            req = self.queue[qi]
            # + token_budget: room for a full step's worth of generated
            # tokens beyond the prompt (1 plain, k+1 speculative).
            if self.cache is not None and not self.cache.can_admit(
                    req.prompt_len + self.token_budget):
                break
            del self.queue[qi]
            slot = self._free_slots.pop()
            req.slot = slot
            req.state = "prefill"
            req.admit_s = now_s
            self.active[slot] = req
            if self.tenants:
                tc = self._tclass(req.tenant)
                self._tenant_pass[req.tenant] = \
                    self._tenant_pass.get(req.tenant, 0.0) \
                    + (req.prompt_len + self.token_budget) / tc.weight
            self._m_requests.labels(event="admitted").inc()
            out.append((slot, req))
        self._update_gauges()
        return out

    def note_handoff(self, req: Request) -> None:
        """prefill -> handoff: a remote prefill worker computed the
        prompt's K/V and its pages are in flight over the KV plane; the
        slot is occupied but NOT decodable until the import lands
        (:meth:`note_prefill` completes the transition)."""
        req.state = "handoff"
        self._m_requests.labels(event="handoff").inc()
        self._update_gauges()

    def note_prefill(self, req: Request, now_s: float) -> None:
        """prefill done: the prompt's KV is resident and the first token
        sampled -- the request joins the decode batch."""
        req.state = "decode"
        req.first_token_s = now_s
        self._m_tokens.labels(phase="prefill").inc(req.prompt_len)
        self._m_tokens.labels(phase="decode").inc()  # the sampled token
        self._m_ttft.observe(max(now_s - req.arrival_s, 0.0))
        self._m_ttft_tenant.labels(tenant=req.tenant).observe(
            max(now_s - req.arrival_s, 0.0))
        # The handoff -> decode transition must surface immediately:
        # the router/control plane count handoff slots as
        # not-yet-decodable capacity.
        self._update_gauges()

    def note_decode_token(self, req: Request, latency_s: float) -> None:
        self._m_tokens.labels(phase="decode").inc()
        self._m_tok_lat.observe(max(latency_s, 0.0))
        req.token_latencies.append(latency_s)

    def note_spec(self, proposed: int, accepted: int) -> None:
        """Account one speculative round: ``proposed`` draft tokens went
        into the verify step, ``accepted`` of them survived (the
        target's bonus token is decode-phase accounting, not a draft).
        Exported as ``horovod_serving_spec_tokens_total{outcome}``."""
        if accepted > proposed:
            raise ValueError(
                f"accepted {accepted} > proposed {proposed}")
        self._m_spec.labels(outcome="proposed").inc(proposed)
        self._m_spec.labels(outcome="accepted").inc(accepted)

    def _release(self, slot: int) -> None:
        """The ONE place a slot and its KV pages return to the pool --
        completion (:meth:`release`) and drain (:meth:`suspend`) both
        land here, so the refcounted page release (shared prefix pages
        decrement; the last holder frees) cannot diverge between
        paths."""
        self._free_slots.append(slot)
        if self.cache is not None:
            self.cache.free_slot(slot)

    def release(self, slot: int, now_s: float, *,
                completed: bool = True) -> Request:
        """done: recycle the slot (and its KV pages) immediately."""
        req = self.active.pop(slot)
        req.state = "done"
        req.done_s = now_s
        req.slot = -1
        self._release(slot)
        self._m_requests.labels(
            event="completed" if completed else "evicted").inc()
        self._update_gauges()
        return req

    # -- drain lifecycle (elastic control plane) ---------------------------
    def pause_admission(self) -> None:
        """Stop moving queued requests into slots (drain is starting).
        Queued requests keep accumulating and admit again on resume."""
        self.admitting = False
        self._update_gauges()

    def resume_admission(self) -> None:
        self.admitting = True
        self._update_gauges()

    def mark_draining(self, slot: int) -> Request:
        """decode -> draining: the slot finishes its request but admits
        no successor; the mesh under it is about to change."""
        req = self.active[slot]
        req.state = "draining"
        self._m_requests.labels(event="draining").inc()
        self._update_gauges()
        return req

    def suspend(self, slot: int) -> Request:
        """draining -> suspended: pull the request out of the batch with
        its progress intact (prompt + emitted tokens) and free the
        slot's KV pages.  The request is NOT done -- it must be
        restored and re-prefilled on the surviving mesh."""
        req = self.active.pop(slot)
        req.state = "suspended"
        req.slot = -1
        self._release(slot)
        self._m_requests.labels(event="suspended").inc()
        self._update_gauges()
        return req

    def restore(self, req: Request) -> int:
        """suspended -> decode on the post-resize mesh: assign a free
        slot; the engine re-prefills prompt + emitted tokens into it."""
        if not self._free_slots:
            raise RuntimeError(
                f"no free slot to restore request {req.rid}")
        slot = self._free_slots.pop()
        req.slot = slot
        req.state = "decode"
        self.active[slot] = req
        self._m_requests.labels(event="reprefill").inc()
        self._update_gauges()
        return slot

    def has_work(self) -> bool:
        return bool(self.queue or self.active)
