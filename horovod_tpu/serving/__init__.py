"""Serving data plane: continuous-batching multi-host inference.

A new CLIENT of the existing exchange stack, not a parallel universe:
the tensor-parallel decode step routes its activation collectives
through ``collectives/ops.py`` (fusion planner / span recorder / static
auditor all see them), per-request lifecycle lands in the PR 6
MetricsRegistry, and per-leg decode time is attributed by the PR 9
span layer exactly like training time.  On top sits the SLO-driven
control plane (``controlplane``/``policy``): autoscale, graceful drain,
and straggler eviction closed-loop over the same elastic resize path
the training loop uses.
"""

from .controlplane import (ControlPlaneReport,  # noqa: F401
                           FleetScaler, ServingControlPlane)
from .decode import (build_decode_step, build_verify_step,  # noqa: F401
                     decode_param_specs, greedy_sample, prefill_forward,
                     stack_adapters, ServingDecodeStep)
from .engine import (RequestPrefetcher, ServingEngine,  # noqa: F401
                     ServingReport)
from .fleet import (DecodeWorker, FleetReport,  # noqa: F401
                    HandoffTicket, PrefillWorker, ServingFleet)
from .kvcache import (CacheConfig, PagedKVCache,  # noqa: F401
                      PrefixCache, cache_sharding)
from .kvwire import (WirePages, decode_kv, encode_kv,  # noqa: F401
                     import_pages, wire_tier)
from .loadgen import (LoadSpec, fleet_spec, generate,  # noqa: F401
                      long_prompt_spec, prefix_spec)
from .policy import (Decision, FleetPolicy,  # noqa: F401
                     FleetPolicyConfig, FleetSample, PolicyConfig,
                     ScalePolicy, SLOSample, valid_tp_sizes)
from .router import FleetRouter  # noqa: F401
from .scheduler import (ContinuousBatchScheduler, Request,  # noqa: F401
                        TenantClass, parse_tenant_classes)
from .spec import ModelDrafter, NgramDrafter  # noqa: F401
