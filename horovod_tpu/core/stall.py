"""Stall inspection + worker heartbeats.

TPU-native analogue of the reference's ``horovod/common/stall_inspector.cc``
(warn when a collective has been outstanding longer than
``HOROVOD_STALL_CHECK_TIME``, optionally shut the job down after
``HOROVOD_STALL_SHUTDOWN_TIME``) re-targeted at the two places a TPU SPMD
runtime can actually stall, per SURVEY.md section 5.2:

* **Blocking waits** (``synchronize()``/``barrier()``/fused-bucket drains):
  under SPMD the reference's rank-divergence class is gone by construction
  (every process compiles the same program), but a peer process dying or a
  wedged device grant leaves ``jax.block_until_ready`` hanging forever.
  :class:`StallInspector` tracks every watched wait and a daemon checker
  thread logs which named ops are stuck and for how long.
* **The launcher/elastic plane**: worker liveness via heartbeat files
  (:class:`HeartbeatWriter` / :func:`heartbeat_age`); the elastic driver
  treats a stale heartbeat like a failed worker (terminate -> blacklist ->
  rescale), replacing the reference's per-tensor cross-rank stall report.

The native cycle scheduler has its own in-C++ stall check for the torch
hook path (``_core/src/core.cc::CheckStalls``); this module covers the
pure-Python paths and the process plane.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("horovod_tpu.stall")


class StallInspector:
    """Watches named blocking operations and complains about stuck ones."""

    def __init__(self, warn_time_s: float = 60.0,
                 shutdown_time_s: float = 0.0,
                 check_interval_s: Optional[float] = None,
                 on_shutdown: Optional[Callable[[List[str]], None]] = None,
                 reset_time_s: float = 0.0,
                 on_reset: Optional[Callable[[List[str]], None]] = None):
        self.warn_time_s = warn_time_s
        self.shutdown_time_s = shutdown_time_s
        # HOROVOD_STALL_RESET_TIME: waits older than this latch the
        # elastic preemption notice, turning a wedged collective into a
        # graceful elastic reset instead of a hang (or the harder
        # os._exit of the shutdown threshold).
        self.reset_time_s = reset_time_s
        self._on_reset = on_reset or self._default_reset
        self._reset_fired = False
        self.check_interval_s = check_interval_s or max(
            min(warn_time_s / 4.0, 10.0), 0.01)
        self._on_shutdown = on_shutdown or self._default_shutdown
        self._lock = threading.Lock()
        self._inflight: Dict[int, Tuple[str, float]] = {}
        self._next_token = 0
        self._last_warn: Dict[int, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- watching ---------------------------------------------------------
    def begin(self, name: str) -> int:
        with self._lock:
            self._next_token += 1
            token = self._next_token
            self._inflight[token] = (name, time.monotonic())
        self._ensure_thread()
        return token

    def end(self, token: int) -> None:
        with self._lock:
            self._inflight.pop(token, None)
            self._last_warn.pop(token, None)

    class _Watch:
        def __init__(self, inspector: "StallInspector", name: str):
            self._i, self._name = inspector, name
            self._token: Optional[int] = None

        def __enter__(self):
            self._token = self._i.begin(self._name)
            return self

        def __exit__(self, *exc):
            self._i.end(self._token)
            return False

    def watch(self, name: str) -> "StallInspector._Watch":
        """Context manager marking a blocking wait as in flight."""
        return self._Watch(self, name)

    def stalled(self) -> List[str]:
        """Names of ops currently past the warn threshold (no logging)."""
        now = time.monotonic()
        with self._lock:
            return [name for name, start in self._inflight.values()
                    if now - start > self.warn_time_s]

    # -- checking ---------------------------------------------------------
    def check_now(self) -> List[str]:
        """One inspection pass; returns the names of currently stalled ops."""
        now = time.monotonic()
        stalled: List[str] = []
        doomed: List[str] = []
        resettable: List[str] = []
        with self._lock:
            for token, (name, start) in self._inflight.items():
                age = now - start
                if age <= self.warn_time_s:
                    continue
                stalled.append(name)
                if now - self._last_warn.get(token, 0.0) > self.warn_time_s:
                    self._last_warn[token] = now
                    logger.warning(
                        "stall inspector: operation %r has been waiting for "
                        "%.1fs (> %.1fs). One or more peer processes may "
                        "have died or a device grant may be wedged.",
                        name, age, self.warn_time_s)
                if self.reset_time_s > 0 and age > self.reset_time_s:
                    resettable.append(name)
                if self.shutdown_time_s > 0 and age > self.shutdown_time_s:
                    doomed.append(name)
        if resettable and not self._reset_fired:
            self._reset_fired = True
            self._on_reset(resettable)
        if doomed:
            self._on_shutdown(doomed)
        return stalled

    @staticmethod
    def _default_shutdown(names: List[str]) -> None:
        logger.critical(
            "stall inspector: operations %s exceeded the shutdown "
            "threshold; aborting the process (HOROVOD_STALL_SHUTDOWN_TIME "
            "semantics).", names)
        os._exit(17)

    @staticmethod
    def _default_reset(names: List[str]) -> None:
        logger.warning(
            "stall inspector: operations %s exceeded "
            "HOROVOD_STALL_RESET_TIME; latching the elastic preemption "
            "notice so the run loop resets instead of hanging.", names)
        try:
            from ..elastic import preemption
            preemption.trigger(f"stall: {', '.join(names)}")
        except ImportError:  # pragma: no cover - partial install
            pass

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-tpu-stall-inspector")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            with self._lock:
                empty = not self._inflight
            if empty:
                continue
            try:
                self.check_now()
            except Exception:  # pragma: no cover - never kill the checker
                logger.exception("stall inspector check failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.check_interval_s + 1)
            self._thread = None


# ---------------------------------------------------------------------------
# Module singleton, configured by hvd.init().
# ---------------------------------------------------------------------------

_inspector: Optional[StallInspector] = None
_inspector_lock = threading.Lock()


def configure(config) -> Optional[StallInspector]:
    """(Re)build the process-wide inspector from a parsed Config."""
    global _inspector
    with _inspector_lock:
        if _inspector is not None:
            _inspector.stop()
            _inspector = None
        if not config.stall_check_disable and config.stall_check_time > 0:
            _inspector = StallInspector(
                warn_time_s=config.stall_check_time,
                shutdown_time_s=config.stall_shutdown_time,
                reset_time_s=getattr(config, "stall_reset_time", 0.0))
        return _inspector


def inspector() -> Optional[StallInspector]:
    return _inspector


def teardown() -> None:
    """Stop and drop the process-wide inspector (shutdown path)."""
    global _inspector
    with _inspector_lock:
        if _inspector is not None:
            _inspector.stop()
            _inspector = None


class _NullWatch:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_WATCH = _NullWatch()


def watched(name: str):
    """``with watched("allreduce.x"):`` around a blocking wait; no-op when
    no inspector is configured."""
    ins = _inspector
    return ins.watch(name) if ins is not None else _NULL_WATCH


# ---------------------------------------------------------------------------
# Heartbeat plane (elastic driver <- worker liveness).
# ---------------------------------------------------------------------------

def heartbeat_path(assignment_path: str, worker_id: str) -> str:
    """Heartbeat file for a worker, next to the elastic assignment file.

    Lives here (not in the elastic modules) so the launcher/driver process
    can compute it without importing jax.
    """
    safe = worker_id.replace("/", "_")
    return os.path.join(os.path.dirname(assignment_path), f"hb_{safe}")


class HeartbeatWriter:
    """Worker-side: touch ``path`` every ``interval_s`` from a daemon thread.

    ``gate`` (when given) is consulted before each beat; returning False
    skips it.  The elastic run loop gates on the stall inspector, so a
    worker wedged inside a blocking collective stops beating and the
    driver's heartbeat timeout can actually evict it -- a live daemon
    thread alone would keep beating through the hang.
    """

    def __init__(self, path: str, interval_s: float = 1.0,
                 gate: Optional[Callable[[], bool]] = None):
        self.path = path
        self.interval_s = interval_s
        self._gate = gate
        self._stop = threading.Event()
        self.beat(force=True)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-tpu-heartbeat")
        self._thread.start()

    def beat(self, force: bool = False) -> None:
        if not force:
            try:
                from ..elastic import chaos as _chaos
                if _chaos.heartbeat_drop_active():
                    return  # injected heartbeat loss (fault testing)
            except ImportError:  # pragma: no cover - partial install
                pass
        if not force and self._gate is not None:
            try:
                if not self._gate():
                    return
            except Exception:  # pragma: no cover - gate must never kill us
                logger.exception("heartbeat gate failed")
        try:
            self._do_beat()
        except OSError:  # pragma: no cover - dir vanished mid-teardown
            pass

    def _do_beat(self) -> None:
        with open(self.path, "a"):
            os.utime(self.path, None)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.interval_s + 1)
        self._cleanup()

    def _cleanup(self) -> None:
        # Remove the file so the driver sees "no heartbeat yet" (which it
        # grants grace) rather than a stale mtime it would treat as a dead
        # worker -- a worker doing post-training work (checkpoint save,
        # eval) after the elastic loop returns must not get evicted.
        try:
            os.unlink(self.path)
        except OSError:
            pass


class KVHeartbeatWriter(HeartbeatWriter):
    """Heartbeats over the HTTP KV rendezvous (multi-host: no shared FS).

    Publishes a wall-clock timestamp under ``hb/<worker_id>``; the driver
    compares against its own clock (same-pod VMs are NTP-synced; the
    heartbeat timeout is seconds, not milliseconds).
    """

    def __init__(self, url: str, worker_id: str, secret_key: str,
                 interval_s: float = 1.0,
                 gate: Optional[Callable[[], bool]] = None):
        from ..run.http_kv import KVClient
        self._kv = KVClient.from_url(url, secret_key, timeout_s=5.0)
        self.worker_id = worker_id
        super().__init__(path=url, interval_s=interval_s, gate=gate)

    def _do_beat(self) -> None:
        try:
            self._kv.put("hb", self.worker_id, repr(time.time()).encode())
        except ConnectionError:  # driver gone/restarting: keep trying
            pass
        except Exception as e:  # RendezvousAuthError etc: NOT transient
            # Surface the misconfiguration loudly ONCE and stop the beat
            # thread cleanly -- a dead daemon thread would hide the cause
            # and the worker would just get evicted as "stale".
            logger.error(
                "heartbeat publication failed permanently (%s); stopping "
                "heartbeats -- the driver will evict this worker after "
                "its heartbeat timeout", e)
            self._stop.set()

    def _cleanup(self) -> None:
        try:
            self._kv.delete("hb", self.worker_id)
        except Exception:  # noqa: BLE001 - best-effort cleanup
            pass


def progress_gate() -> bool:
    """Default heartbeat gate: healthy unless the stall inspector sees a
    wait past its warn threshold."""
    ins = _inspector
    return ins is None or not ins.stalled()


def heartbeat_age(path: str) -> Optional[float]:
    """Seconds since the last beat, or None if no heartbeat exists yet."""
    try:
        return max(0.0, time.time() - os.stat(path).st_mtime)
    except OSError:
        return None
