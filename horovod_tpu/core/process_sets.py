"""Process sets: named subsets of ranks with their own communicator.

Analogue of ``horovod/common/process_set.cc`` + ``horovod/common/process_sets.py``
(each set owns a controller/communicator; dynamic registration via
``hvd.add_process_set``).  Here a "rank" is a *device index* in the global
mesh order and the per-set communicator is either

* a sub-:class:`jax.sharding.Mesh` over the member devices (for the eager
  collective path), or
* a masked full-mesh collective for in-step use (every device executes the
  same SPMD program; non-members contribute the op's identity and keep
  their own value -- see ``collectives.ops._resolve``).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

from .exceptions import ProcessSetError
from .state import global_state
from ..parallel.mesh import HVD_AXIS

GLOBAL_PROCESS_SET_NAME = "global"


@dataclasses.dataclass(frozen=True)
class ProcessSet:
    """A named subset of device ranks."""

    name: str
    ranks: Tuple[int, ...]  # global device indices, sorted

    def size(self) -> int:
        return len(self.ranks)

    def included(self, rank: int) -> bool:
        return rank in self.ranks

    def is_global(self) -> bool:
        return self.name == GLOBAL_PROCESS_SET_NAME

    def mesh(self) -> Mesh:
        """The set's communicator mesh: the global mesh for the world set
        (possibly hierarchical), a flat sub-mesh otherwise."""
        st = global_state()
        if st.mesh is None:
            raise ProcessSetError("not initialized")
        if self.is_global():
            return st.mesh
        return self.flat_mesh()

    def flat_mesh(self) -> Mesh:
        """1-D ``hvd``-axis mesh over the member devices (eager path)."""
        st = global_state()
        if st.mesh is None:
            raise ProcessSetError("not initialized")
        import numpy as np
        flat = list(st.mesh.devices.flat)
        devs = np.asarray([flat[r] for r in self.ranks], dtype=object)
        return Mesh(devs, (HVD_AXIS,))

def _require_init() -> None:
    if not global_state().initialized:
        raise ProcessSetError("call horovod_tpu.init() before using process sets")


def add_process_set(ranks: Sequence[int], name: Optional[str] = None) -> ProcessSet:
    """Register a new process set (``hvd.add_process_set`` parity)."""
    _require_init()
    st = global_state()
    ranks = tuple(sorted(int(r) for r in ranks))
    n = int(st.mesh.devices.size)
    if len(set(ranks)) != len(ranks):
        raise ProcessSetError(f"duplicate ranks in {ranks}")
    if not ranks or ranks[0] < 0 or ranks[-1] >= n:
        raise ProcessSetError(f"ranks {ranks} out of range for world size {n}")
    if name is None:
        name = "ps_" + "_".join(map(str, ranks))
    with st.lock:
        if name in st.process_sets:
            existing = st.process_sets[name]
            if existing.ranks != ranks:
                raise ProcessSetError(
                    f"process set {name!r} already exists with ranks "
                    f"{existing.ranks}")
            return existing
        ps = ProcessSet(name=name, ranks=ranks)
        st.process_sets[name] = ps
        return ps


def remove_process_set(name_or_set) -> None:
    """Deregister a set by name or ProcessSet object (the reference's
    ``hvd.remove_process_set`` takes the object)."""
    _require_init()
    name = name_or_set.name if isinstance(name_or_set, ProcessSet) \
        else name_or_set
    if name == GLOBAL_PROCESS_SET_NAME:
        raise ProcessSetError("cannot remove the global process set")
    st = global_state()
    with st.lock:
        st.process_sets.pop(name, None)


def get_process_set(name_or_set=None) -> ProcessSet:
    """Resolve ``None`` | name | ProcessSet to a registered ProcessSet."""
    _require_init()
    st = global_state()
    if name_or_set is None:
        return st.process_sets[GLOBAL_PROCESS_SET_NAME]
    if isinstance(name_or_set, ProcessSet):
        return name_or_set
    try:
        return st.process_sets[name_or_set]
    except KeyError:
        raise ProcessSetError(f"unknown process set {name_or_set!r}") from None


def process_set_names() -> List[str]:
    _require_init()
    return sorted(global_state().process_sets)


def _install_global_set() -> ProcessSet:
    """Called by ``init()``: register the world set."""
    st = global_state()
    n = int(st.mesh.devices.size)
    ps = ProcessSet(name=GLOBAL_PROCESS_SET_NAME, ranks=tuple(range(n)))
    st.process_sets[GLOBAL_PROCESS_SET_NAME] = ps
    return ps
