"""Core lifecycle + identity API: ``init/shutdown/rank/size/...``.

TPU-native analogue of the reference's ctypes surface
(``horovod/common/basics.py::HorovodBasics`` -> ``horovod/common/operations.cc``
C API).  The reference's ``InitializeHorovodOnce`` spawns a background
coordinator thread and boots MPI/Gloo; here ``init()`` (optionally) boots
the JAX distributed runtime, builds the communicator :class:`Mesh` over the
ICI/DCN fabric and registers the global process set.  No background thread
exists -- SPMD makes runtime tensor negotiation unnecessary.

Rank semantics under SPMD (documented divergence from the reference, where
one process == one GPU == one rank):

* ``size()``   -- total number of *devices* (data-parallel workers).
* ``rank()``   -- this controller process's index (``jax.process_index()``).
  In the launcher's one-device-per-process mode this equals the Horovod
  rank exactly; in single-process multi-device mode it is 0 and per-device
  identity is available in-step via ``axis_index()``.
* ``local_rank()/local_size()`` -- position among processes on this host /
  devices owned by this process.
* ``cross_rank()/cross_size()`` -- host (slice) index / count.
"""

from __future__ import annotations

import atexit
import logging
from typing import Optional, Sequence

import jax

from .config import Config, load_config
from .exceptions import NotInitializedError
from .state import global_state
from . import process_sets as _ps
from ..parallel import mesh as _mesh

logger = logging.getLogger("horovod_tpu")


def _setup_logging(level: str, hide_timestamp: bool = False) -> None:
    lvl = {"trace": logging.DEBUG, "debug": logging.DEBUG,
           "info": logging.INFO, "warning": logging.WARNING,
           "error": logging.ERROR, "fatal": logging.CRITICAL}.get(
               level.lower(), logging.WARNING)
    # HOROVOD_LOG_HIDE_TIMESTAMP parity (reference logging.cc):
    # timestamps on by default, hideable via the parsed config.
    fmt = "%(name)s %(levelname)s: %(message)s" if hide_timestamp else \
        "%(asctime)s %(name)s %(levelname)s: %(message)s"
    logging.basicConfig(level=lvl, format=fmt)
    logger.setLevel(lvl)


def init(
    devices: Optional[Sequence[jax.Device]] = None,
    hierarchical: Optional[bool] = None,
    process_sets: Optional[Sequence[Sequence[int]]] = None,
    config: Optional[Config] = None,
    mesh=None,
) -> None:
    """Initialize the framework (``hvd.init()`` parity).

    Args:
      devices: devices forming the world communicator; default all devices.
      hierarchical: force the 2-D ``(dcn, ici)`` mesh; default: on when
        multiple processes are present or ``HOROVOD_HIERARCHICAL_ALLREDUCE``
        is set.
      process_sets: extra process sets to register, as lists of ranks
        (``hvd.init(process_sets=...)`` parity).
      config: explicit config (tests); default: parsed from environment.
    """
    st = global_state()
    with st.lock:
        if st.initialized:
            return
        cfg = config if config is not None else load_config()
        _setup_logging(cfg.log_level, cfg.log_hide_timestamp)

        if cfg.force_cpu:
            # Must run before any backend initialization; the TPU plugin's
            # sitecustomize pre-sets jax_platforms, so the env var alone is
            # not enough.
            try:
                jax.config.update("jax_platforms", "cpu")
            except RuntimeError:
                logger.warning("force_cpu set but backends already "
                               "initialized; continuing on %s",
                               jax.default_backend())

        if cfg.compile_cache:
            # Persistent XLA compilation cache: pays the big-model compile
            # once per program fingerprint (BERT-Large: ~35 min through
            # the tunnelled runtime, ~seconds on a cache hit).
            jax.config.update("jax_compilation_cache_dir",
                              cfg.compile_cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              1.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              0)

        # Multi-process bootstrap: the launcher hands us a coordinator
        # address (HOROVOD_GLOO_RENDEZVOUS_ADDR analogue) and our process
        # identity; jax.distributed is the rendezvous+control plane.
        if cfg.coordinator_addr and not jax._src.distributed.global_state.client:
            addr = cfg.coordinator_addr
            if cfg.coordinator_port:
                addr = f"{addr}:{cfg.coordinator_port}"
            kwargs = {}
            if cfg.env_size > 0:
                kwargs["num_processes"] = cfg.env_size
            if cfg.env_rank >= 0:
                kwargs["process_id"] = cfg.env_rank
            logger.info("jax.distributed.initialize(%s, %s)", addr, kwargs)
            jax.distributed.initialize(addr, **kwargs)
            st.owns_distributed = True

        if devices is None:
            devices = jax.devices()
        # Topology spec (HOROVOD_HIERARCHICAL=auto|rows,cols) pins the
        # two-level mesh shape; the legacy boolean only turns it on with
        # the process-grouped layout.
        spec_hier, dcn_size = _mesh.parse_topology_spec(
            cfg.hierarchical, len(devices))
        if hierarchical is None:
            hierarchical = (spec_hier or cfg.hierarchical_allreduce
                            or jax.process_count() > 1)
        st.config = cfg
        st.mesh = mesh if mesh is not None else \
            _mesh.build_mesh(devices, hierarchical=hierarchical,
                             dcn_size=dcn_size if hierarchical else None)
        st.initialized = True
        _ps._install_global_set()
        if process_sets:
            for ranks in process_sets:
                _ps.add_process_set(ranks)

        from ..controller.cache import ExecutableCache
        st.cache = ExecutableCache(capacity=cfg.cache_capacity)
        if cfg.timeline:
            from ..timeline import Timeline
            st.timeline = Timeline(cfg.timeline,
                                   mark_cycles=cfg.timeline_mark_cycles,
                                   rank=jax.process_index())
        if cfg.autotune:
            from ..autotune import Autotuner
            st.autotuner = Autotuner(cfg)
        if cfg.metrics_enabled:
            from ..timeline import metrics as _metrics
            _metrics.install_default_metrics()
            if cfg.metrics_port >= 0:
                from ..run.metrics_server import MetricsServer
                st.metrics_server = MetricsServer(port=cfg.metrics_port)
                logger.info("Prometheus /metrics on port %d",
                            st.metrics_server.port)
        elif cfg.metrics_port >= 0:
            logger.warning("HOROVOD_METRICS_PORT set but HOROVOD_METRICS=0; "
                           "not starting the metrics endpoint")
        # Span layer: tag this process's spans with its rank and mirror
        # them into the timeline when one is open; when metrics are on,
        # arm the straggler monitor on the recorder's step boundary.
        from ..timeline import spans as _spans
        rec = _spans.recorder().configure(rank=jax.process_index(),
                                          timeline=st.timeline)
        if cfg.metrics_enabled:
            from ..timeline.straggler import StragglerMonitor
            st.straggler = StragglerMonitor(
                world=jax.process_count(),
                stall_check_time=cfg.stall_check_time)
            rec.add_listener(st.straggler.observe)
        if cfg.trace_sync:
            _install_trace_plane(st, cfg, rec)
        from . import stall as _stall
        _stall.configure(cfg)
        # Deterministic fault injection (HOROVOD_CHAOS): installed once
        # per process, keyed to the process rank so every worker resolves
        # the same schedule.  No-op without the env var.
        from ..elastic import chaos as _chaos
        _chaos.maybe_install(rank=jax.process_index(),
                             size=jax.process_count())
        global _atexit_registered
        if not _atexit_registered:
            atexit.register(_atexit_shutdown)
            _atexit_registered = True
        logger.info(
            "horovod_tpu initialized: %d device(s), mesh axes %s, "
            "process %d/%d", int(st.mesh.devices.size), st.mesh.axis_names,
            jax.process_index(), jax.process_count())


def _install_trace_plane(st, cfg: Config, rec) -> None:
    """Arm the cross-rank trace plane (HOROVOD_TRACE_SYNC=1): NTP-style
    clock offset against the rendezvous KV server + per-step summary
    publication.  The KV endpoint comes from the elastic assignment URL
    (``HVD_TPU_ELASTIC_ASSIGNMENT=http://...`` + the per-job secret);
    without one this degrades to a warning, never an init failure."""
    import os as _os
    from ..elastic.notify import ASSIGNMENT_ENV
    from ..run.secret import SECRET_ENV
    url = _os.environ.get(ASSIGNMENT_ENV, "")
    secret = _os.environ.get(SECRET_ENV)
    if not url.startswith("http://") or not secret:
        logger.warning(
            "HOROVOD_TRACE_SYNC=1 but no HTTP KV rendezvous is "
            "configured (%s/%s); skipping clock alignment",
            ASSIGNMENT_ENV, SECRET_ENV)
        return
    try:
        from ..run.http_kv import KVClient
        from ..timeline.sync import TracePlane
        kv = KVClient.from_url(url, secret, timeout_s=5.0)
        st.trace_plane = TracePlane(
            kv, rank=jax.process_index(), size=jax.process_count(),
            publish_steps=cfg.trace_publish_steps, monitor=st.straggler)
        rec.add_listener(st.trace_plane.on_summary)
    except Exception as e:  # ConnectionError, auth, ... -- telemetry only
        logger.warning("trace plane disabled: %s", e)


_atexit_registered = False


def _atexit_shutdown() -> None:
    st = global_state()
    if st.initialized:
        try:
            shutdown()
        except Exception:  # pragma: no cover - best effort at interpreter exit
            pass


def shutdown() -> None:
    """Tear down framework state (``hvd.shutdown()`` parity)."""
    import sys
    if "horovod_tpu.torch_api.batching" in sys.modules:
        sys.modules["horovod_tpu.torch_api.batching"].shutdown_batcher()
    from ..collectives import eager as _eager
    _eager.reset_fences()
    st = global_state()
    with st.lock:
        if not st.initialized:
            return
        owns = st.owns_distributed
        st.reset()
        from . import stall as _stall
        _stall.teardown()
    if owns:
        try:
            jax.distributed.shutdown()
        except Exception:  # pragma: no cover
            logger.warning("jax.distributed.shutdown failed", exc_info=True)


def is_initialized() -> bool:
    return global_state().initialized


def _require_init() -> "GlobalStateT":
    st = global_state()
    if not st.initialized:
        raise NotInitializedError()
    return st


def mesh():
    """The world communicator mesh."""
    return _require_init().mesh


def reduce_axes():
    """Axis name(s) collectives reduce over, innermost last."""
    return tuple(_require_init().mesh.axis_names)


def size() -> int:
    """Total number of data-parallel workers (devices)."""
    return int(_require_init().mesh.devices.size)


def rank() -> int:
    _require_init()
    return jax.process_index()


def local_size() -> int:
    _require_init()
    return jax.local_device_count()


def local_rank() -> int:
    st = _require_init()
    if st.config.env_local_rank >= 0:
        return st.config.env_local_rank
    return 0


def cross_size() -> int:
    st = _require_init()
    if st.config.env_cross_size >= 0:
        return st.config.env_cross_size
    return jax.process_count()


def cross_rank() -> int:
    st = _require_init()
    if st.config.env_cross_rank >= 0:
        return st.config.env_cross_rank
    return jax.process_index()


def is_homogeneous() -> bool:
    """True when every process owns the same device count."""
    _require_init()
    return True


# Build-capability probes (parity with HorovodBasics.{nccl,mpi,...}_built).
def nccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_built() -> bool:
    return False


def tpu_built() -> bool:
    return True


def mpi_threads_supported() -> bool:
    return False


def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    """Start (or restart) timeline capture at runtime
    (``hvd.start_timeline`` parity; the env-driven path is
    ``HOROVOD_TIMELINE`` at init).  Like the reference, requires
    ``init()`` first -- init would otherwise silently replace (and leak)
    a pre-init timeline via its ``HOROVOD_TIMELINE`` path."""
    from ..timeline import Timeline
    from .exceptions import NotInitializedError

    if not is_initialized():
        raise NotInitializedError(
            "hvd.start_timeline() requires hvd.init() first")
    st = global_state()
    with st.lock:
        if st.timeline is not None:
            st.timeline.close()
        st.timeline = Timeline(file_path, mark_cycles=mark_cycles,
                               rank=jax.process_index())
        from ..timeline import spans as _spans
        _spans.recorder().configure(timeline=st.timeline)


def stop_timeline() -> None:
    """Stop timeline capture and finalize the trace file
    (``hvd.stop_timeline`` parity)."""
    st = global_state()
    with st.lock:
        if st.timeline is not None:
            st.timeline.close()
            st.timeline = None
            from ..timeline import spans as _spans
            _spans.recorder().timeline = None
