"""Debug-mode desync detection (checksums across the mesh).

The reference has no equivalent subsystem -- its single-background-thread
design plus the StallInspector covered the divergence failure modes of a
rank-per-process runtime (SURVEY.md section 5.2).  Under SPMD the dangerous
class is different: every *process* holds what it believes is a replica of
the model state, and a bug (non-deterministic host input, a missed
broadcast after restore, reading params outside the donated step) silently
diverges replicas until the loss explodes.  SURVEY.md 5.2 prescribes "a
debug mode that checksums (psum of hashes) to detect desync -- cheap on
TPU"; this module is that mode, enabled with ``HOROVOD_CHECK_DESYNC=1``.

Two entry points:

* :func:`check_desync` -- host-level: CRC32 every leaf of a pytree,
  allgather the checksum vectors across the world, and raise
  :class:`~horovod_tpu.DesyncError` (a ``HorovodInternalError`` subclass)
  naming the leaves that differ.  Wired into
  ``hvd.elastic`` ``State.commit()`` when the debug flag is on (the commit
  boundary is exactly where a silent desync would get checkpointed).
* :func:`horovod_tpu.collectives.ops.desync_check` -- in-step: an integer
  bit-sum compared via pmax/pmin inside the traced program (see ops.py).
* :func:`tripwire_check` -- the SDC corruption tripwire
  (``HOROVOD_DESYNC_CHECK_STEPS``): one jitted shard_map computes a
  per-DEVICE bit-checksum of the replicated params and allgathers the
  vector; the host majority-votes and raises
  :class:`~horovod_tpu.core.exceptions.CorruptRankError` naming the
  minority rank(s), which the elastic plane quarantines.
"""

from __future__ import annotations

import zlib
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from .exceptions import CorruptRankError, DesyncError


def _canonical_bytes(obj, _depth: int = 0) -> bytes:
    """Deterministic, version-stable byte encoding of a non-array leaf.

    Pickle bytes are NOT stable across python/numpy minor versions (the
    protocol's framing and numpy's reconstructor paths both change),
    which made cross-rank comparison on heterogeneous hosts a
    false-positive source.  This encoding depends only on the VALUE:
    type-tagged reprs for scalars (float repr is the shortest round-trip
    form, stable since python 3.1), recursive tagged encodings for
    containers, with dict items sorted by encoded key and set elements
    sorted by encoded value so iteration order never leaks in.
    """
    if _depth > 64:
        raise TypeError("leaf nests too deeply for canonical encoding")
    if obj is None or isinstance(obj, (bool, int)):
        return f"{type(obj).__name__}:{obj!r}".encode()
    if isinstance(obj, float):
        return b"float:" + repr(obj).encode()
    if isinstance(obj, complex):
        return (b"complex:" + repr(obj.real).encode() + b"," +
                repr(obj.imag).encode())
    if isinstance(obj, str):
        return b"str:" + obj.encode("utf-8", "surrogatepass")
    if isinstance(obj, (bytes, bytearray)):
        return b"bytes:" + bytes(obj)
    if isinstance(obj, (list, tuple)):
        parts = [_canonical_bytes(v, _depth + 1) for v in obj]
        tag = b"list" if isinstance(obj, list) else b"tuple"
        return tag + b"[" + b";".join(parts) + b"]"
    if isinstance(obj, dict):
        items = sorted(
            (_canonical_bytes(k, _depth + 1),
             _canonical_bytes(v, _depth + 1)) for k, v in obj.items())
        return b"dict{" + b";".join(k + b"=" + v for k, v in items) + b"}"
    if isinstance(obj, (set, frozenset)):
        parts = sorted(_canonical_bytes(v, _depth + 1) for v in obj)
        return b"set{" + b";".join(parts) + b"}"
    # Plain objects: type-tagged instance state (what pickle would ship),
    # NEVER the default repr -- that embeds the memory address, the
    # round-2 review's false-desync case.
    state = getattr(obj, "__dict__", None)
    if isinstance(state, dict):
        return (b"obj:" + type(obj).__qualname__.encode()
                + _canonical_bytes(state, _depth + 1))
    raise TypeError(f"no canonical encoding for {type(obj).__qualname__}")


def _leaf_checksum(leaf) -> int:
    """Stable CRC32 of a leaf's host bytes (uint32).

    Non-array leaves are checksummed via :func:`_canonical_bytes` -- a
    value-only encoding that (unlike ``repr``) never embeds per-process
    memory addresses and (unlike pickle) is stable across python/numpy
    minor versions on heterogeneous hosts.  Leaves with no canonical
    encoding contribute only their type name -- such a leaf is
    under-checked, never a false positive.
    """
    try:
        a = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
        if a.dtype == object:
            raise TypeError
        return zlib.crc32(a.tobytes())
    except (TypeError, ValueError):
        pass
    try:
        return zlib.crc32(_canonical_bytes(leaf))
    except Exception:  # noqa: BLE001 - unencodable leaf
        return zlib.crc32(type(leaf).__qualname__.encode())


def tree_checksums(tree: Any) -> Tuple[List[str], np.ndarray]:
    """(leaf paths, per-leaf CRC32 vector) for a pytree."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    paths = [jax.tree_util.keystr(kp) or "<root>" for kp, _ in flat]
    sums = np.array([_leaf_checksum(v) for _, v in flat], dtype=np.int64)
    return paths, sums


def mismatched_rows(rows: np.ndarray, paths: List[str]) -> List[str]:
    """Leaf paths whose checksum differs across the rank rows."""
    if rows.size == 0:
        return []
    diff = (rows != rows[0:1]).any(axis=0)
    return [p for p, d in zip(paths, diff) if d]


def check_desync(tree: Any, name: str = "state", process_set=None,
                 raise_error: bool = True) -> List[str]:
    """Verify ``tree`` is bit-identical on every process in the set.

    Each process CRC32s its host view of every leaf; the checksum vectors
    are allgathered and compared.  Returns the paths of mismatched leaves
    (and raises :class:`~horovod_tpu.DesyncError` unless
    ``raise_error=False``).

    In single-process mode every rank shares one host copy, so this
    degenerates to a cheap no-op check -- the interesting case is the
    launcher's one-process-per-device mode.
    """
    from ..collectives import eager as _eager
    from ..core import process_sets as _ps

    ps = _ps.get_process_set(process_set)
    paths, sums = tree_checksums(tree)
    if not paths:
        return []
    local = _eager.replicated_stack(sums, ps)
    out = _eager.allgather(local, name=f"desync.{name}", process_set=ps)
    # Row 0 of the local result is this rank's copy of the concatenation of
    # every rank's checksum vector.
    row = _eager.local_result(out)[0]
    rows = np.asarray(row).reshape(ps.size(), len(paths))
    bad = mismatched_rows(rows, paths)
    if bad and raise_error:
        raise DesyncError(
            f"desync detected in {name!r}: {len(bad)} leaf/leaves differ "
            f"across ranks: {bad[:8]}{'...' if len(bad) > 8 else ''} -- a "
            f"replica of the model state has diverged (missed broadcast "
            f"after restore, or non-deterministic update?)", leaves=bad)
    return bad


def maybe_check(tree: Any, name: str = "state",
                process_set=None) -> Optional[List[str]]:
    """Run :func:`check_desync` only when ``HOROVOD_CHECK_DESYNC`` is on."""
    from .state import global_state
    st = global_state()
    if not st.initialized or st.config is None or not st.config.check_desync:
        return None
    return check_desync(tree, name=name, process_set=process_set)


# --- cross-rank corruption tripwire (SDC defense plane) -------------------


def _traced_bit_checksum(x):
    """uint32 position-weighted wrapping bit-sum of a local array.

    Same construction as ``collectives.ops.desync_check`` (Knuth-constant
    odd weights, exact under any reduction order); here the per-device
    value is KEPT rather than pmax/pmin-compared, because the tripwire
    needs attribution, not just a boolean.
    """
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(x)
    nbits = x.dtype.itemsize * 8
    if x.dtype == jnp.bool_:
        bits = x.astype(jnp.int32)
    elif nbits >= 32:
        bits = lax.bitcast_convert_type(x, jnp.int32)
    elif jnp.issubdtype(x.dtype, jnp.floating):
        bits = lax.bitcast_convert_type(
            x, jnp.dtype(f"int{nbits}")).astype(jnp.int32)
    else:
        bits = x.astype(jnp.int32)
    flat = bits.ravel()
    if not flat.size:
        return jnp.zeros((), jnp.uint32)
    u = lax.bitcast_convert_type(flat, jnp.uint32)
    w = (jnp.arange(flat.size, dtype=jnp.uint32)
         * jnp.uint32(2654435761)) | jnp.uint32(1)
    return jnp.sum(u * w, dtype=jnp.uint32)


_TRIPWIRE_CACHE: dict = {}


def build_tripwire(mesh=None):
    """Jitted ``tree -> uint32[world]`` per-device replica checksums.

    A SEPARATE executable from the train step (the tripwire samples every
    ``HOROVOD_DESYNC_CHECK_STEPS`` steps; folding it into the step trace
    would charge every step for it): one shard_map in which each device
    checksums ITS OWN replica of the tree and an all_gather exposes the
    whole vector for host-side majority voting.
    """
    from jax.sharding import PartitionSpec as P

    from ..collectives import ops as _ops
    from . import basics as _basics

    mesh = mesh if mesh is not None else _basics.mesh()
    fn = _TRIPWIRE_CACHE.get(mesh)
    if fn is not None:
        return fn
    axes = tuple(mesh.axis_names)

    def local(tree):
        import jax.numpy as jnp
        c = jnp.zeros((), jnp.uint32)
        for leaf in jax.tree.leaves(tree):
            # 31x combine keeps leaf order significant, like the
            # per-position weights keep element order significant.
            c = c * jnp.uint32(31) + _traced_bit_checksum(leaf)
        # Routed through the ops layer (axis resolution + plan audit);
        # device order is mesh-major, same as jax.devices().
        return _ops.allgather(c[None], axes=axes, tiled=True).reshape(-1)

    shard = jax.shard_map(local, mesh=mesh, in_specs=(P(),),
                          out_specs=P(), check_vma=False)
    fn = jax.jit(shard)
    _TRIPWIRE_CACHE[mesh] = fn
    return fn


def tripwire_check(tree: Any, mesh=None, name: str = "params",
                   raise_error: bool = True) -> List[int]:
    """Cross-rank corruption tripwire: attribute divergent replicas.

    Every device checksums its replica of ``tree``; a device whose
    checksum disagrees with the strict majority holds a corrupt replica
    (bitflip-class SDC -- finite values the numeric guard cannot see).
    Returns the minority device indices and raises
    :class:`CorruptRankError` (unless ``raise_error=False``) so the
    elastic plane can quarantine them through the eviction/resize path.
    Without a strict majority no attribution is possible and the error
    carries an empty rank list (handled as a plain desync: restore).
    """
    from ..timeline import metrics as _metrics

    rows = np.asarray(jax.device_get(build_tripwire(mesh)(tree)))
    reg = _metrics.registry()
    reg.counter("horovod_guard_tripwire_checks_total",
                "Cross-rank corruption tripwire samples").inc()
    vals, counts = np.unique(rows, return_counts=True)
    if len(vals) <= 1:
        return []
    reg.counter("horovod_guard_tripwire_trips_total",
                "Tripwire samples that found divergent replicas").inc()
    majority = vals[np.argmax(counts)]
    bad = [] if counts.max() * 2 <= rows.size else \
        [int(i) for i in np.nonzero(rows != majority)[0]]
    if raise_error:
        raise CorruptRankError(
            f"corruption tripwire: {name!r} replicas diverge across the "
            f"mesh (checksums {rows.tolist()}); "
            + (f"minority rank(s) {bad} attributed for quarantine"
               if bad else "no strict majority, cannot attribute"),
            ranks=bad)
    return bad


def corrupt_replica(tree: Any, rank: int, mesh=None, bit: int = 0) -> Any:
    """Flip one bit in device ``rank``'s replica of the first float leaf.

    Chaos-injection helper (``bitflip@`` kind): rebuilds the leaf with
    ``jax.make_array_from_single_device_arrays`` so exactly one device's
    copy differs -- byte 0's bit ``bit`` (the mantissa LSB for little-
    endian floats), a finite perturbation no numeric screen can see.
    This is precisely the fault class only the tripwire catches.
    """
    import jax.numpy as jnp

    from . import basics as _basics

    mesh = mesh if mesh is not None else _basics.mesh()
    devices = list(mesh.devices.flat)
    if not 0 <= int(rank) < len(devices):
        raise ValueError(f"rank {rank} outside mesh of {len(devices)}")
    victim = devices[int(rank)]
    leaves, treedef = jax.tree.flatten(tree)
    idx = next((i for i, v in enumerate(leaves)
                if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                and jnp.asarray(v).size), None)
    if idx is None:
        raise ValueError("corrupt_replica: no floating leaf to corrupt")
    leaf = leaves[idx]
    host = np.asarray(jax.device_get(leaf))
    bufs = []
    for d in devices:
        a = np.array(host, copy=True)
        if d == victim:
            raw = a.view(np.uint8)
            raw.reshape(-1)[0] ^= np.uint8(1 << (int(bit) & 7))
        bufs.append(jax.device_put(a, d))
    leaves[idx] = jax.make_array_from_single_device_arrays(
        host.shape, leaf.sharding, bufs)
    return jax.tree.unflatten(treedef, leaves)
