"""Debug-mode desync detection (checksums across the mesh).

The reference has no equivalent subsystem -- its single-background-thread
design plus the StallInspector covered the divergence failure modes of a
rank-per-process runtime (SURVEY.md section 5.2).  Under SPMD the dangerous
class is different: every *process* holds what it believes is a replica of
the model state, and a bug (non-deterministic host input, a missed
broadcast after restore, reading params outside the donated step) silently
diverges replicas until the loss explodes.  SURVEY.md 5.2 prescribes "a
debug mode that checksums (psum of hashes) to detect desync -- cheap on
TPU"; this module is that mode, enabled with ``HOROVOD_CHECK_DESYNC=1``.

Two entry points:

* :func:`check_desync` -- host-level: CRC32 every leaf of a pytree,
  allgather the checksum vectors across the world, and raise
  :class:`~horovod_tpu.DesyncError` (a ``HorovodInternalError`` subclass)
  naming the leaves that differ.  Wired into
  ``hvd.elastic`` ``State.commit()`` when the debug flag is on (the commit
  boundary is exactly where a silent desync would get checkpointed).
* :func:`horovod_tpu.collectives.ops.desync_check` -- in-step: an integer
  bit-sum compared via pmax/pmin inside the traced program (see ops.py).
"""

from __future__ import annotations

import pickle
import zlib
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from .exceptions import DesyncError


def _leaf_checksum(leaf) -> int:
    """Stable CRC32 of a leaf's host bytes (uint32).

    Non-array leaves are checksummed via their pickle bytes, which (unlike
    ``repr``) never embed per-process memory addresses.  Leaves that cannot
    be pickled contribute only their type name -- such a leaf is
    under-checked, never a false positive.  Caveat: containers whose
    iteration order depends on the string hash seed (sets of strings) can
    still pickle differently across processes; run workers with a fixed
    ``PYTHONHASHSEED`` when such leaves are in elastic state.
    """
    try:
        a = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
        if a.dtype == object:
            raise TypeError
        return zlib.crc32(a.tobytes())
    except (TypeError, ValueError):
        pass
    try:
        return zlib.crc32(pickle.dumps(leaf, protocol=4))
    except Exception:  # noqa: BLE001 - unpicklable leaf
        return zlib.crc32(type(leaf).__qualname__.encode())


def tree_checksums(tree: Any) -> Tuple[List[str], np.ndarray]:
    """(leaf paths, per-leaf CRC32 vector) for a pytree."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    paths = [jax.tree_util.keystr(kp) or "<root>" for kp, _ in flat]
    sums = np.array([_leaf_checksum(v) for _, v in flat], dtype=np.int64)
    return paths, sums


def mismatched_rows(rows: np.ndarray, paths: List[str]) -> List[str]:
    """Leaf paths whose checksum differs across the rank rows."""
    if rows.size == 0:
        return []
    diff = (rows != rows[0:1]).any(axis=0)
    return [p for p, d in zip(paths, diff) if d]


def check_desync(tree: Any, name: str = "state", process_set=None,
                 raise_error: bool = True) -> List[str]:
    """Verify ``tree`` is bit-identical on every process in the set.

    Each process CRC32s its host view of every leaf; the checksum vectors
    are allgathered and compared.  Returns the paths of mismatched leaves
    (and raises :class:`~horovod_tpu.DesyncError` unless
    ``raise_error=False``).

    In single-process mode every rank shares one host copy, so this
    degenerates to a cheap no-op check -- the interesting case is the
    launcher's one-process-per-device mode.
    """
    from ..collectives import eager as _eager
    from ..core import process_sets as _ps

    ps = _ps.get_process_set(process_set)
    paths, sums = tree_checksums(tree)
    if not paths:
        return []
    local = _eager.replicated_stack(sums, ps)
    out = _eager.allgather(local, name=f"desync.{name}", process_set=ps)
    # Row 0 of the local result is this rank's copy of the concatenation of
    # every rank's checksum vector.
    row = _eager.local_result(out)[0]
    rows = np.asarray(row).reshape(ps.size(), len(paths))
    bad = mismatched_rows(rows, paths)
    if bad and raise_error:
        raise DesyncError(
            f"desync detected in {name!r}: {len(bad)} leaf/leaves differ "
            f"across ranks: {bad[:8]}{'...' if len(bad) > 8 else ''} -- a "
            f"replica of the model state has diverged (missed broadcast "
            f"after restore, or non-deterministic update?)", leaves=bad)
    return bad


def maybe_check(tree: Any, name: str = "state",
                process_set=None) -> Optional[List[str]]:
    """Run :func:`check_desync` only when ``HOROVOD_CHECK_DESYNC`` is on."""
    from .state import global_state
    st = global_state()
    if not st.initialized or st.config is None or not st.config.check_desync:
        return None
    return check_desync(tree, name=name, process_set=process_set)
