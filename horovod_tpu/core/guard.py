"""Silent-data-corruption (SDC) guard: in-step numeric screen policy.

Large fleets lose runs to *wrong* values, not just dead ranks: a flipped
bit or a NaN sails through every collective (the exchange is correctness-
agnostic), poisons the error-feedback residuals, and is committed forever.
The reference framework has stall detection but nothing numeric; this
module is the host half of the defense plane:

* ``HOROVOD_GUARD=auto|1|0`` decides, at step-BUILD time, whether the
  train-step builders compile the screen into the trace (a global
  nonfinite count plus a gradient-magnitude psum riding alongside the
  existing loss allreduce -- one extra ``f32[2]`` psum per step).  The
  in-trace policy selects the OLD params/opt-state wholesale on a
  poisoned step, so a skipped step leaves params and EF residuals
  bitwise untouched.
* :class:`GuardPolicy` consumes the per-step guard vector
  ``[nonfinite, grad_norm, skipped]`` on the host, feeds the
  ``horovod_guard_*`` metric family, and raises
  :class:`~horovod_tpu.core.exceptions.SustainedAnomalyError` after
  ``HOROVOD_GUARD_STREAK`` consecutive skips so the elastic loop /
  snapshot ledger rolls back instead of spinning on a poisoned input.

``auto`` (the default) arms the guard only when a corruption scenario is
plausibly in play -- a corruption chaos kind (``bitflip``/``nan``)
installed, desync checks on, the snapshot ledger or the cross-rank
tripwire enabled -- so default-config traces stay bitwise identical to
an unguarded build (the scan-loop parity and audit baselines never see
a guard leg they did not ask for).  Latency/availability chaos kinds
(``slow``, ``kill``, ...) do NOT arm it: they cannot corrupt numerics,
and timing drills expect attribution-neutral steps.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .exceptions import SustainedAnomalyError

_TRUE = ("1", "on", "true", "yes")
_FALSE = ("0", "off", "false", "no")


def _config():
    from .state import global_state
    return global_state().config


def resolve_mode(config=None) -> bool:
    """Should the builders compile the guard screen into the step trace?

    Resolved once per step BUILD (not per call): the screen changes the
    traced program.  ``1``/``0`` force; ``auto`` arms iff a corruption
    chaos kind (bitflip/nan) is installed or any of ``check_desync`` /
    ``desync_check_steps`` / ``snapshot_steps`` is active.
    """
    cfg = _config() if config is None else config
    mode = (getattr(cfg, "guard", "auto") or "auto").strip().lower() \
        if cfg is not None else "auto"
    if mode in _TRUE:
        return True
    if mode in _FALSE:
        return False
    if mode != "auto":
        raise ValueError(
            f"HOROVOD_GUARD must be auto|1|0, got {mode!r}")
    if cfg is None:
        return False
    if cfg.check_desync or cfg.desync_check_steps > 0 \
            or cfg.snapshot_steps > 0:
        return True
    from ..elastic import chaos
    return chaos.corruption_armed()


def step_guard(config=None) -> Tuple[bool, float]:
    """``(enabled, norm_limit)`` for the train-step builders."""
    cfg = _config() if config is None else config
    enabled = resolve_mode(cfg)
    limit = float(getattr(cfg, "guard_norm_limit", 0.0) or 0.0) \
        if cfg is not None else 0.0
    return enabled, limit


class GuardPolicy:
    """Host-side consumer of the in-step guard vector.

    ``observe`` takes the step's ``[nonfinite, grad_norm, skipped]`` row
    (or the ``[k, 3]`` stack a scan loop emits), updates the
    ``horovod_guard_*`` metrics, and tracks the consecutive-skip streak.
    A streak reaching ``streak_limit`` raises
    :class:`SustainedAnomalyError` -- the signal that skipping alone is
    not recovering the run and the rollback ledger must engage.
    """

    def __init__(self, streak_limit: int = 3):
        self.streak_limit = max(1, int(streak_limit))
        self.streak = 0
        self.steps = 0
        self.skipped = 0

    def observe(self, rows) -> int:
        """Consume guard rows; returns how many steps were skipped."""
        rows = np.atleast_2d(np.asarray(rows, dtype=np.float64))
        from ..timeline import metrics as _metrics
        reg = _metrics.registry()
        steps_c = reg.counter(
            "horovod_guard_steps_total",
            "Train steps screened by the SDC guard")
        skip_c = reg.counter(
            "horovod_guard_skipped_total",
            "Optimizer updates skipped by the SDC guard (poisoned steps)")
        skipped_here = 0
        last_norm = None
        for row in rows:
            self.steps += 1
            steps_c.inc()
            if float(row[2]) > 0.0:
                self.skipped += 1
                self.streak += 1
                skipped_here += 1
                skip_c.inc()
            else:
                self.streak = 0
            last_norm = float(row[1])
        if last_norm is not None:
            reg.gauge(
                "horovod_guard_grad_norm",
                "Global gradient-magnitude screen from the last guarded "
                "step (-1 when nonfinite)").set(
                last_norm if np.isfinite(last_norm) else -1.0)
        reg.gauge(
            "horovod_guard_streak",
            "Consecutive guard-skipped steps (rollback trips at "
            "HOROVOD_GUARD_STREAK)").set(float(self.streak))
        if self.streak >= self.streak_limit:
            raise SustainedAnomalyError(self.streak)
        return skipped_here


_policy: Optional[GuardPolicy] = None


def policy() -> GuardPolicy:
    """Process-wide policy singleton (streak limit from config)."""
    global _policy
    if _policy is None:
        cfg = _config()
        _policy = GuardPolicy(
            streak_limit=getattr(cfg, "guard_streak", 3) if cfg else 3)
    return _policy


def reset() -> None:
    """Drop the singleton (tests; re-init picks up fresh config)."""
    global _policy
    _policy = None
