"""Environment-driven configuration.

TPU-native analogue of the reference's env parser
(``horovod/common/utils/env_parser.cc`` -- translates ``HOROVOD_*`` env vars
into global-state flags).  We honour both the historical ``HOROVOD_*`` names
(for drop-in parity) and ``HVD_TPU_*`` overrides (which win when both are
set).

Unlike the reference there is no C++ GlobalState to populate: the config is a
frozen dataclass read once at ``hvd.init()`` time and stored on the
:class:`horovod_tpu.core.state.GlobalState` singleton.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

_MiB = 1024 * 1024


def _env(name: str, default: Optional[str] = None) -> Optional[str]:
    """Look up ``HVD_TPU_<name>`` then ``HOROVOD_<name>``."""
    for prefix in ("HVD_TPU_", "HOROVOD_"):
        v = os.environ.get(prefix + name)
        if v is not None:
            return v
    return default


def _env_int(name: str, default: int) -> int:
    v = _env(name)
    return int(v) if v not in (None, "") else default


def _env_float(name: str, default: float) -> float:
    v = _env(name)
    return float(v) if v not in (None, "") else default


def _env_bool(name: str, default: bool = False) -> bool:
    v = _env(name)
    if v in (None, ""):
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class Config:
    """Runtime knobs.

    Mirrors the de-facto public config API of the reference (SURVEY.md
    section 5.6).  Fields that only make sense for a CUDA runtime (NCCL
    stream counts, D2D memcpy batching) are intentionally absent: XLA owns
    scheduling on TPU.
    """

    # Fusion-buffer analogue: gradient bucketing threshold in bytes.
    # Reference: HOROVOD_FUSION_THRESHOLD (default 64 MiB).
    fusion_threshold: int = 64 * _MiB

    # Executable-cache capacity (ResponseCache analogue).
    # Reference: HOROVOD_CACHE_CAPACITY (default 1024).
    cache_capacity: int = 1024

    # Eager-path micro-batch window in milliseconds (HOROVOD_CYCLE_TIME):
    # how long the native scheduler waits to fuse hook-enqueued gradients.
    cycle_time: float = 1.0

    # Two-level DCN x ICI reduction (NCCLHierarchicalAllreduce analogue).
    hierarchical_allreduce: bool = False

    # Two-level mesh topology spec (HOROVOD_HIERARCHICAL):
    # ``auto`` derives the slice axis from the process grouping /
    # elastic assignment; ``rows,cols`` pins explicit (dcn, ici)
    # extents (virtual multi-slice dry runs).  Setting it implies
    # hierarchical_allreduce.  Parsed by
    # ``parallel.mesh.parse_topology_spec``.
    hierarchical: Optional[str] = None

    # Chrome-trace timeline output path (HOROVOD_TIMELINE).
    timeline: Optional[str] = None
    timeline_mark_cycles: bool = False

    # Autotune (HOROVOD_AUTOTUNE / HOROVOD_AUTOTUNE_LOG).
    autotune: bool = False
    autotune_log: Optional[str] = None

    # ZeRO-1 sharded optimizer state (HOROVOD_ZERO=1): default zero_stage
    # for steps built without an explicit argument (optim/zero.py).
    zero_stage: int = 0

    # Steps-per-execution scan loop (HOROVOD_STEPS_PER_EXEC): default k for
    # make_train_loop / make_flax_train_loop built without an explicit
    # steps_per_execution argument.  k steps compile into ONE lax.scan
    # executable, so they cost one host dispatch and one device->host fence.
    steps_per_exec: int = 1

    # Microbatched backward-overlap exchange (HOROVOD_MICROBATCHES):
    # default k for train steps built without an explicit ``microbatches``
    # argument.  The per-step batch splits into k sub-batches inside ONE
    # compiled executable; each sub-batch's gradient buckets reduce-scatter
    # while the next sub-batch's backward pass is still running, so the
    # latency-hiding scheduler can overlap wire time with FLOPs.
    microbatches: int = 1

    # Fused deferred-async flush (HOROVOD_DEFERRED_FUSE, default on).
    # At a flush point, compatible pending ``*_async`` ops (same kind,
    # dtype, process set, codec, pre/postscale) pack into fusion-planner
    # buckets and dispatch ONE collective + ONE fence per bucket instead
    # of one per op -- the eager-path analogue of the reference's
    # fusion-buffer cycle.  Off = round-5 per-op dispatch (still one
    # presence round per flush).
    deferred_fuse: bool = True

    # Per-rank bucket size cap in bytes for the fused deferred flush
    # (HOROVOD_DEFERRED_FUSE_THRESHOLD); 0 = follow fusion_threshold.
    deferred_fuse_threshold: int = 0

    # Default gradient-exchange codec (HOROVOD_COMPRESSION): a spec string
    # parsed by ``collectives.compression.parse_compression`` --
    # none|fp16|bf16|fp8|powersgd:<rank>|topk:<fraction>.  Applies to
    # DistributedOptimizer wraps built without an explicit ``compression``
    # argument; None = no compression.
    compression: Optional[str] = None

    # Error-feedback residual carry for the powersgd/topk codecs
    # (HOROVOD_EF_RESIDUAL, default on).  Off drops each step's
    # compression error instead of feeding it back -- ablation only, it
    # biases convergence.
    ef_residual: bool = True

    # 3-D parallelism defaults for train steps built without explicit
    # arguments (training.py).  HOROVOD_TP: tensor-parallel degree --
    # params shard over the mesh's "model" axis and the TP collectives
    # (row-parallel allreduce) run inside a slice.  HOROVOD_PIPELINE_STAGES:
    # pipeline-stage count over the "pipe" axis.  1 = off (pure DP,
    # bitwise-identical traces to the pre-3D build).
    tp: int = 1
    pipeline_stages: int = 1

    # MoE all-to-all wire codec (HOROVOD_MOE_COMPRESSION): none|bf16|fp16.
    # Casts the dispatch/combine slot buffers before each all_to_all and
    # restores f32 after -- the expert-parallel analogue of the gradient
    # exchange codecs.  The autotuner's MoE axis (HOROVOD_AUTOTUNE_MOE=1)
    # overrides this per sample.
    moe_compression: Optional[str] = None

    # Chunked gradient exchange (HOROVOD_EXCHANGE_CHUNK_MB, megabytes;
    # 0 disables).  Decomposes each fusion bucket's allreduce into
    # chunk-sized reduce-scatter + all-gather pairs so XLA's latency-hiding
    # scheduler can interleave communication with remaining backward
    # compute (all-gather compiles async on this toolchain; a monolithic
    # all-reduce does not).
    exchange_chunk_bytes: int = 0

    # Stall/heartbeat inspector for the launcher/elastic plane.
    stall_check_disable: bool = False
    stall_check_time: float = 60.0
    stall_shutdown_time: float = 0.0
    # Waits older than this latch the elastic preemption notice (a
    # wedged collective becomes an elastic reset, not a hang); 0 = off.
    stall_reset_time: float = 0.0

    # Elastic.
    elastic_timeout: float = 600.0

    # Logging (HOROVOD_LOG_LEVEL, HOROVOD_LOG_HIDE_TIMESTAMP).
    log_level: str = "warning"
    log_hide_timestamp: bool = False

    # Launcher-provided identity (HOROVOD_RANK/SIZE/... parity); -1 = unset.
    env_rank: int = -1
    env_size: int = -1
    env_local_rank: int = -1
    env_local_size: int = -1
    env_cross_rank: int = -1
    env_cross_size: int = -1

    # Coordinator/rendezvous (HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT analogue):
    # address handed to jax.distributed.initialize.
    coordinator_addr: Optional[str] = None
    coordinator_port: int = 0

    # Debug-mode desync checksums (no reference equivalent; SURVEY.md 5.2).
    check_desync: bool = False
    # Consecutive restore+sync attempts before a persistent desync aborts.
    desync_max_retries: int = 3

    # Silent-data-corruption defense plane (core/guard.py).
    # HOROVOD_GUARD=auto|1|0 compiles a cheap numeric screen (global
    # nonfinite count + grad norm, one extra f32[2] psum) into every train
    # step and skips the optimizer update on a poisoned step.  "auto"
    # enables the guard only when a corruption scenario is plausibly in
    # play (chaos injection, desync checks, snapshot ledger) so default
    # traces stay bitwise identical to the unguarded build.
    guard: str = "auto"
    # Skip a step whose global grad norm exceeds this bound even when
    # finite (HOROVOD_GUARD_NORM_LIMIT); 0 = nonfinite screening only.
    guard_norm_limit: float = 0.0
    # Consecutive guard-skipped steps before the anomaly counts as
    # sustained and the rollback ledger engages (HOROVOD_GUARD_STREAK).
    guard_streak: int = 3
    # Snapshot/rollback ledger cadence in committed steps
    # (HOROVOD_SNAPSHOT_STEPS); 0 disables the ring.
    snapshot_steps: int = 0
    # In-band cross-rank corruption tripwire cadence in steps
    # (HOROVOD_DESYNC_CHECK_STEPS); 0 disables.  Unlike check_desync
    # (every commit, debug-only) this samples every N train steps and
    # attributes the corrupt rank for quarantine.
    desync_check_steps: int = 0

    # Driver-side heartbeat eviction (seconds; 0 disables).  Workers whose
    # elastic heartbeat file goes stale longer than this are terminated and
    # blacklisted (HOROVOD_STALL_SHUTDOWN_TIME analogue at process level).
    heartbeat_timeout: float = 0.0

    # Force the XLA:CPU backend before first device use (the launcher's
    # --cpu test mode; the Gloo-CPU-backend analogue).
    force_cpu: bool = False

    # Metrics plane (timeline/metrics.py).  HOROVOD_METRICS=0 disables the
    # registry entirely (family accessors hand back a shared no-op object
    # and the train-step StepReport instrumentation unwraps -- zero
    # overhead).  HOROVOD_METRICS_PORT >= 0 serves Prometheus text on
    # that port at hvd.init() (0 = ephemeral; read the bound port from
    # global_state().metrics_server.port); -1 = no HTTP endpoint.
    metrics_enabled: bool = True
    metrics_port: int = -1

    # Cross-rank trace plane (timeline/sync.py).  HOROVOD_TRACE_SYNC=1:
    # at init() each rank estimates its clock offset to the rendezvous
    # KV server (NTP-style ping over http_kv) and publishes a compact
    # per-step span summary every HOROVOD_TRACE_PUBLISH_STEPS steps;
    # rank 0 merges them and feeds the straggler monitor.  Requires a
    # reachable KV server (elastic/launcher runs); no-op without one.
    trace_sync: bool = False
    trace_publish_steps: int = 10

    # Persistent XLA compilation cache directory (HOROVOD_COMPILE_CACHE /
    # HVD_TPU_COMPILE_CACHE).  Big-model compiles through the tunnelled
    # runtime take tens of minutes (BERT-Large: ~35 min); the cache pays
    # them once per program fingerprint.  No reference equivalent (CUDA
    # kernels ship precompiled); on TPU it is table stakes.
    compile_cache: Optional[str] = None


# The fixed port worker 0 serves the JAX coordination service on when
# the pod environment does not name one (matches jax's own TPU cluster
# detection, so mixed bootstrap paths still rendezvous).
TPU_POD_COORDINATOR_PORT = 8476


def detect_tpu_pod() -> Optional[dict]:
    """Multi-host Cloud TPU slice environment -> process identity.

    On a multi-host TPU slice the runtime exports
    ``TPU_WORKER_HOSTNAMES`` (comma-separated, worker 0 first) and
    ``TPU_WORKER_ID`` (this host's index; older images spell it
    ``CLOUD_TPU_TASK_ID``).  This is the pod-native analogue of the
    launcher's LSF allocation detection (``run/lsf.py``) and of the
    reference inheriting placement from ``mpirun`` (SURVEY.md 4.4):
    ``hvd.init()`` on each pod host bootstraps unaided, with worker 0
    hosting the coordination service.  Explicit ``HOROVOD_RANK``/
    ``HVD_TPU_COORDINATOR_ADDR`` always win; disable detection entirely
    with ``HOROVOD_NO_TPU_POD_DETECT=1``.

    Returns ``{"addr", "port", "rank", "size"}`` or ``None`` when not on
    a multi-host slice (single-host slices need no coordination).
    """
    if _env_bool("NO_TPU_POD_DETECT"):
        return None
    names = [h.strip() for h in
             os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
             if h.strip()]
    if len(names) < 2:
        return None
    # Like _env_int, a set-but-empty variable counts as unset (a wrapper
    # exporting TPU_WORKER_ID= must not mask a valid CLOUD_TPU_TASK_ID).
    wid = os.environ.get("TPU_WORKER_ID", "").strip() or \
        os.environ.get("CLOUD_TPU_TASK_ID", "").strip()
    if not wid.isdigit():
        return None
    rank = int(wid)
    if rank >= len(names):
        return None
    return {"addr": names[0], "port": TPU_POD_COORDINATOR_PORT,
            "rank": rank, "size": len(names)}


def load_config() -> Config:
    """Parse the environment into a :class:`Config`."""
    addr = _env("COORDINATOR_ADDR") or _env("GLOO_RENDEZVOUS_ADDR")
    port = _env_int("COORDINATOR_PORT", _env_int("GLOO_RENDEZVOUS_PORT", 0))
    env_rank = _env_int("RANK", -1)
    env_size = _env_int("SIZE", -1)
    env_local_rank = _env_int("LOCAL_RANK", -1)
    env_local_size = _env_int("LOCAL_SIZE", -1)
    env_cross_rank = _env_int("CROSS_RANK", -1)
    env_cross_size = _env_int("CROSS_SIZE", -1)
    if addr is None:
        pod = detect_tpu_pod()
        if pod is not None:
            addr = pod["addr"]
            if port == 0:
                port = pod["port"]
            if env_rank < 0:
                env_rank = pod["rank"]
            if env_size < 0:
                env_size = pod["size"]
            # One process per pod host: host index IS the cross rank.
            if env_cross_rank < 0:
                env_cross_rank = pod["rank"]
            if env_cross_size < 0:
                env_cross_size = pod["size"]
            if env_local_rank < 0:
                env_local_rank = 0
            if env_local_size < 0:
                env_local_size = 1
    return Config(
        fusion_threshold=_env_int("FUSION_THRESHOLD", 64 * _MiB),
        cache_capacity=_env_int("CACHE_CAPACITY", 1024),
        cycle_time=_env_float("CYCLE_TIME", 1.0),
        hierarchical_allreduce=_env_bool("HIERARCHICAL_ALLREDUCE"),
        hierarchical=_env("HIERARCHICAL"),
        timeline=_env("TIMELINE"),
        timeline_mark_cycles=_env_bool("TIMELINE_MARK_CYCLES"),
        autotune=_env_bool("AUTOTUNE"),
        autotune_log=_env("AUTOTUNE_LOG"),
        zero_stage=_env_int("ZERO", 0),
        steps_per_exec=_env_int("STEPS_PER_EXEC", 1),
        microbatches=_env_int("MICROBATCHES", 1),
        tp=_env_int("TP", 1),
        pipeline_stages=_env_int("PIPELINE_STAGES", 1),
        moe_compression=_env("MOE_COMPRESSION"),
        compression=_env("COMPRESSION"),
        ef_residual=_env_bool("EF_RESIDUAL", True),
        deferred_fuse=_env_bool("DEFERRED_FUSE", True),
        deferred_fuse_threshold=_env_int("DEFERRED_FUSE_THRESHOLD", 0),
        exchange_chunk_bytes=_env_int("EXCHANGE_CHUNK_MB", 0) * _MiB,
        stall_check_disable=_env_bool("STALL_CHECK_DISABLE"),
        # Upstream spells these *_TIME_SECONDS; accept both spellings.
        stall_check_time=_env_float(
            "STALL_CHECK_TIME_SECONDS", _env_float("STALL_CHECK_TIME", 60.0)),
        stall_shutdown_time=_env_float(
            "STALL_SHUTDOWN_TIME_SECONDS",
            _env_float("STALL_SHUTDOWN_TIME", 0.0)),
        stall_reset_time=_env_float(
            "STALL_RESET_TIME_SECONDS",
            _env_float("STALL_RESET_TIME", 0.0)),
        elastic_timeout=_env_float("ELASTIC_TIMEOUT", 600.0),
        log_level=_env("LOG_LEVEL", "warning") or "warning",
        log_hide_timestamp=_env_bool("LOG_HIDE_TIMESTAMP"),
        env_rank=env_rank,
        env_size=env_size,
        env_local_rank=env_local_rank,
        env_local_size=env_local_size,
        env_cross_rank=env_cross_rank,
        env_cross_size=env_cross_size,
        coordinator_addr=addr,
        coordinator_port=port,
        compile_cache=_env("COMPILE_CACHE"),
        check_desync=_env_bool("CHECK_DESYNC"),
        desync_max_retries=_env_int("DESYNC_MAX_RETRIES", 3),
        guard=(_env("GUARD", "auto") or "auto").strip().lower(),
        guard_norm_limit=_env_float("GUARD_NORM_LIMIT", 0.0),
        guard_streak=_env_int("GUARD_STREAK", 3),
        snapshot_steps=_env_int("SNAPSHOT_STEPS", 0),
        desync_check_steps=_env_int("DESYNC_CHECK_STEPS", 0),
        heartbeat_timeout=_env_float("HEARTBEAT_TIMEOUT", 0.0),
        force_cpu=_env_bool("FORCE_CPU"),
        metrics_enabled=_env_bool("METRICS", True),
        metrics_port=_env_int("METRICS_PORT", -1),
        trace_sync=_env_bool("TRACE_SYNC"),
        trace_publish_steps=_env_int("TRACE_PUBLISH_STEPS", 10),
    )
