"""Exception types.

Parity with the reference's ``horovod/common/exceptions.py``: the two
exception classes are the *control-flow protocol* of elastic training
(SURVEY.md section 4.5) -- a failed collective raises
:class:`HorovodInternalError` (roll back to last commit), a topology change
pushed by the driver raises :class:`HostsUpdatedInterrupt` (graceful
re-rendezvous at the next commit boundary).
"""

from __future__ import annotations


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """A collective or runtime operation failed (e.g. a peer vanished).

    Elastic training catches this and restores from the last committed
    state.  Reference: ``horovod/common/exceptions.py::HorovodInternalError``.
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """The set of hosts/slices changed; re-rendezvous at next commit.

    Reference: ``horovod/common/exceptions.py::HostsUpdatedInterrupt``.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class DesyncError(HorovodInternalError):
    """Replica state diverged across ranks (debug-mode checksums).

    Raised by the ``HOROVOD_CHECK_DESYNC=1`` commit-boundary check *before*
    the diverged values overwrite the last good snapshot.  Subclasses
    :class:`HorovodInternalError` so generic elastic handlers (restore from
    last commit) catch it; the run loop special-cases it first to skip the
    re-rendezvous (no membership change happened).
    """

    def __init__(self, message: str, leaves=None):
        super().__init__(message)
        self.leaves = list(leaves or [])


class SustainedAnomalyError(HorovodInternalError):
    """The SDC guard skipped ``streak`` consecutive steps.

    One poisoned step is absorbed in-trace (the guard selects the old
    params/opt-state, bitwise); a sustained streak means the anomaly is
    not transient -- a wedged input shard, a corrupt replica -- and
    skipping forward cannot recover.  Subclasses
    :class:`HorovodInternalError` so the elastic loop's restore-from-
    last-commit path catches it; the snapshot ledger
    (``elastic/state.py``) turns that restore into a rollback + replay.
    """

    def __init__(self, streak: int):
        super().__init__(
            f"SDC guard skipped {streak} consecutive steps; "
            "rolling back to last good snapshot")
        self.streak = int(streak)


class CorruptRankError(DesyncError):
    """The cross-rank tripwire attributed divergent state to rank(s).

    Raised by :func:`horovod_tpu.core.desync.tripwire_check` when the
    per-rank parameter checksums disagree AND a majority agrees on one
    value: the minority rank(s) hold corrupt replicas (bitflip-class
    SDC).  Carries the attributed ranks so the elastic plane can
    quarantine them (evict + resize) instead of restarting blind.
    """

    def __init__(self, message: str, ranks=None, leaves=None):
        super().__init__(message, leaves=leaves)
        self.ranks = sorted(set(int(r) for r in (ranks or [])))


class NotInitializedError(HorovodTpuError):
    """An API was called before ``hvd.init()``."""

    def __init__(self, what: str = "Horovod-TPU"):
        super().__init__(
            f"{what} has not been initialized; call horovod_tpu.init() first."
        )


class ProcessSetError(HorovodTpuError):
    """Invalid process-set operation (unknown set, bad ranks, ...)."""
