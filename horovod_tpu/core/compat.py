"""JAX version compatibility shims.

The codebase targets the current ``jax.shard_map`` API (top-level, with
the ``check_vma`` flag).  Older toolchains (jax <= 0.4.x) ship the same
functionality as ``jax.experimental.shard_map.shard_map`` with the flag
spelled ``check_rep``.  Installing the adapter once at package import
keeps every call site on the modern spelling.
"""

from __future__ import annotations

import jax


def _install_shard_map() -> None:
    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # pragma: no cover - no known jax lacks both
        return

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map


def _install_axis_size() -> None:
    from jax import lax
    if hasattr(lax, "axis_size"):
        return

    def axis_size(axis_name):
        # psum of a Python constant over a named axis is evaluated
        # statically, yielding the axis size as a concrete int.
        return lax.psum(1, axis_name)

    lax.axis_size = axis_size


_install_shard_map()
_install_axis_size()
