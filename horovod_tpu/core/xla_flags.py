"""Latency-hiding XLA / libtpu flag pack for the backward-overlap exchange.

The microbatched train step (``training.py``, ``microbatches=k``) emits the
per-bucket ``reduce-scatter`` of microbatch *i* between the backward segments
of microbatch *i+1*, but the emitted schedule only turns into *wall-clock*
overlap when the compiler (a) runs collectives asynchronously and (b) uses
the latency-hiding scheduler to sink compute between collective-start and
collective-done.  On TPU those behaviours sit behind XLA/libtpu flags that
must be set **before** the backend initialises.

This module assembles the recommended pack and applies it to the process
environment, returning an inspectable :class:`FlagReport` of what was
applied vs. rejected and why.  Design rules:

* **No-op on CPU.**  The flags are TPU-only; on the CPU backend (tests,
  laptops) every flag is rejected with reason ``"cpu backend"`` and the
  environment is left untouched.
* **User flags win.**  A flag the user already set in ``XLA_FLAGS`` /
  ``LIBTPU_INIT_ARGS`` is never overridden (reason ``"user-set"``).
* **Too late is an error, not a surprise.**  If the JAX backend is already
  initialised the pack cannot take effect; every flag is rejected with
  reason ``"backend already initialized"`` rather than silently exported.

Typical use (before ``horovod_tpu.init()``)::

    from horovod_tpu.core import xla_flags
    report = xla_flags.apply_xla_flags()
    print(report.summary())
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Mapping, MutableMapping, Optional, Tuple

# The pack.  Keyed by the environment variable each flag belongs to:
# ``XLA_FLAGS`` feeds the host-side XLA compiler, ``LIBTPU_INIT_ARGS``
# feeds libtpu at device initialisation.  Values are the full
# ``--flag=value`` strings appended (space-separated) to the variable.
XLA_FLAG_PACK: Dict[str, Tuple[str, ...]] = {
    "XLA_FLAGS": (
        # Sink independent compute between collective start/done pairs.
        "--xla_tpu_enable_latency_hiding_scheduler=true",
        # Run all-gathers (the microbatch finalize's single AG) async.
        "--xla_enable_async_all_gather=true",
        "--xla_enable_async_collective_permute=true",
    ),
    "LIBTPU_INIT_ARGS": (
        # Fuse the per-bucket reduce-scatters with surrounding compute into
        # async pairs so backward(i+1) runs during exchange(i).
        "--xla_tpu_enable_async_collective_fusion=true",
        "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
        "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
        # Let the tensor cores keep computing while the collective engine
        # drains the wire (the hardware side of backward-overlap).
        "--xla_tpu_overlap_compute_collective_tc=true",
        "--xla_tpu_enable_data_parallel_all_reduce_opt=true",
        "--xla_tpu_data_parallel_opt_different_sized_ops=true",
    ),
}


def _flag_name(flag: str) -> str:
    """``--xla_foo=true`` -> ``--xla_foo`` (identity for valueless flags)."""
    return flag.split("=", 1)[0]


@dataclasses.dataclass(frozen=True)
class FlagReport:
    """What :func:`apply_xla_flags` did, flag by flag.

    ``applied`` maps env-var name to the tuple of flags appended to it;
    ``rejected`` maps each skipped flag to its reason (``"cpu backend"``,
    ``"user-set"``, or ``"backend already initialized"``).
    """

    platform: str
    applied: Dict[str, Tuple[str, ...]]
    rejected: Dict[str, str]

    @property
    def applied_flags(self) -> Tuple[str, ...]:
        return tuple(f for flags in self.applied.values() for f in flags)

    @property
    def is_noop(self) -> bool:
        return not self.applied_flags

    def summary(self) -> str:
        lines = [f"xla_flags: platform={self.platform} "
                 f"applied={len(self.applied_flags)} "
                 f"rejected={len(self.rejected)}"]
        for var, flags in sorted(self.applied.items()):
            for f in flags:
                lines.append(f"  + {var}: {f}")
        for f, why in sorted(self.rejected.items()):
            lines.append(f"  - {f}  ({why})")
        return "\n".join(lines)


def detect_platform(env: Optional[Mapping[str, str]] = None) -> str:
    """Best-effort platform guess from the environment, without importing
    jax (importing jax can itself initialise a backend).

    ``JAX_PLATFORMS`` / ``JAX_PLATFORM_NAME`` win when set; otherwise the
    presence of a libtpu install marks TPU, else ``"cpu"``.
    """
    env = os.environ if env is None else env
    for var in ("JAX_PLATFORMS", "JAX_PLATFORM_NAME"):
        val = env.get(var, "").strip().lower()
        if val:
            # "tpu,cpu" means TPU-first; take the first entry.
            return val.split(",")[0].strip()
    try:
        import importlib.util
        if importlib.util.find_spec("libtpu") is not None:
            return "tpu"
    except (ImportError, ValueError):
        pass
    return "cpu"


def apply_xla_flags(
    env: Optional[MutableMapping[str, str]] = None,
    platform: Optional[str] = None,
    pack: Optional[Mapping[str, Tuple[str, ...]]] = None,
) -> FlagReport:
    """Append the latency-hiding pack to ``env``, honouring the rules in
    the module docstring.  Returns a :class:`FlagReport`; mutates ``env``
    (default ``os.environ``) only for applied flags.
    """
    real_env = env is None
    env = os.environ if env is None else env
    pack = XLA_FLAG_PACK if pack is None else pack
    platform = detect_platform(env) if platform is None else platform
    all_flags = [(var, f) for var, flags in pack.items() for f in flags]

    if platform != "tpu":
        return FlagReport(platform=platform, applied={},
                          rejected={f: "cpu backend" for _, f in all_flags})

    # Only probe the live backend when operating on the real environment;
    # an explicit env dict is a dry run / test harness.
    if real_env:
        from ..utils.platform import backend_initialized
        if backend_initialized():
            return FlagReport(
                platform=platform, applied={},
                rejected={f: "backend already initialized"
                          for _, f in all_flags})

    applied: Dict[str, Tuple[str, ...]] = {}
    rejected: Dict[str, str] = {}
    for var, flags in pack.items():
        existing = env.get(var, "")
        present = {_flag_name(tok) for tok in existing.split() if tok}
        added = []
        for f in flags:
            if _flag_name(f) in present:
                rejected[f] = "user-set"
            else:
                added.append(f)
        if added:
            env[var] = (existing + " " + " ".join(added)).strip()
            applied[var] = tuple(added)
    return FlagReport(platform=platform, applied=applied, rejected=rejected)


_last_report: Optional[FlagReport] = None


def apply(env: Optional[MutableMapping[str, str]] = None,
          platform: Optional[str] = None) -> FlagReport:
    """Convenience wrapper that records the report for later inspection
    via :func:`last_report` (e.g. from ``bench.py``'s config dump)."""
    global _last_report
    _last_report = apply_xla_flags(env=env, platform=platform)
    return _last_report


def last_report() -> Optional[FlagReport]:
    return _last_report
