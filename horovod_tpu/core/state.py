"""Process-global framework state.

Analogue of the reference's ``horovod/common/global_state.h::HorovodGlobalState``
singleton (controller, op manager, process-set table, fusion buffer,
parameter manager, timeline, flags).  Here the members are: the device mesh
(the communicator), the process-set table, the executable cache
(ResponseCache analogue), the timeline writer and the parsed config.

There is deliberately no background thread: under SPMD every process
compiles the same fused program, so the negotiation machine the reference's
background loop exists for has no work to do (SURVEY.md section 7).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, TYPE_CHECKING

from .config import Config

if TYPE_CHECKING:  # pragma: no cover - import cycle guards
    from jax.sharding import Mesh
    from .process_sets import ProcessSet
    from ..controller.cache import ExecutableCache
    from ..timeline import Timeline
    from ..autotune import Autotuner


class GlobalState:
    """Mutable singleton holding everything ``init()`` sets up."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.initialized: bool = False
        self.config: Optional[Config] = None
        self.mesh: Optional["Mesh"] = None
        self.process_sets: Dict[str, "ProcessSet"] = {}
        self.cache: Optional["ExecutableCache"] = None
        self.timeline: Optional["Timeline"] = None
        self.autotuner: Optional["Autotuner"] = None
        # Prometheus /metrics endpoint (run/metrics_server.py), started by
        # init() when HOROVOD_METRICS_PORT >= 0.
        self.metrics_server = None
        # Cross-rank trace plane (timeline/sync.py::TracePlane), armed by
        # init() under HOROVOD_TRACE_SYNC=1 with a reachable KV server.
        self.trace_plane = None
        # Straggler monitor (timeline/straggler.py), armed whenever
        # metrics are enabled; fed by the SpanRecorder step boundary.
        self.straggler = None
        # True when this process called jax.distributed.initialize and owns
        # a shutdown obligation.
        self.owns_distributed: bool = False

    def reset(self) -> None:
        self.initialized = False
        self.config = None
        self.mesh = None
        self.process_sets = {}
        self.cache = None
        if self.timeline is not None:
            self.timeline.close()
        self.timeline = None
        self.autotuner = None
        if self.metrics_server is not None:
            self.metrics_server.stop()
        self.metrics_server = None
        self.trace_plane = None
        self.straggler = None
        try:
            from ..timeline import spans as _spans
            _spans.recorder().reset()
        except ImportError:  # pragma: no cover - partial install
            pass
        self.owns_distributed = False
        # Preemption machinery is keyed to the runtime lifecycle: stop
        # the GCE poll thread and forget the handler-installed latch so
        # repeated init/reset cycles don't leak pollers (the pending
        # notice, if any, survives -- see preemption.on_runtime_reset).
        try:
            from ..elastic import preemption
            preemption.on_runtime_reset()
        except ImportError:  # pragma: no cover - partial install
            pass


_state = GlobalState()


def global_state() -> GlobalState:
    return _state
