"""Distributed-training estimators (``horovod/spark`` Estimator parity).

Reference surface (``horovod/spark/keras/KerasEstimator``,
``horovod/spark/torch/TorchEstimator``, SURVEY.md section 3.6): an
Estimator materializes a DataFrame into rank-sharded intermediate storage
(Petastorm in the reference; npz shards under the :class:`Store` here),
``fit()`` launches ``num_proc`` workers that train with the framework's
``DistributedOptimizer`` over the framework collectives, rank 0
checkpoints the result through the Store, and the returned Model
transforms new data with the trained weights.

TPU-native differences: workers are spawned through the local executor
(one process per slot, CPU backend in tests -- the Spark barrier-mode
path is used when pyspark is importable and a Spark DataFrame is passed);
the JAX estimator is the flagship, with torch and keras estimators riding
their respective API shims so reference users can keep their model
objects.

Input flexibility: ``fit`` accepts a dict of numpy arrays, a pandas
DataFrame + ``feature_cols``/``label_cols``, a pyspark DataFrame
(partitions STREAM to Store chunks through ``toLocalIterator`` -- the
driver never materializes the dataset, the Petastorm-scale path), or any
iterator/generator of such items (each item becomes one streamed chunk).
"""

from __future__ import annotations

import io
import os
import pickle
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .store import LocalStore, Store

__all__ = ["EstimatorParams", "JaxEstimator", "JaxModel", "TorchEstimator",
           "TorchModel", "KerasEstimator", "KerasModel",
           "LightningEstimator"]


# ---------------------------------------------------------------------------
# data plumbing
# ---------------------------------------------------------------------------

def _as_arrays(df, feature_cols, label_cols) -> Dict[str, np.ndarray]:
    """Normalize any supported input into {'features': ..., 'labels': ...}."""
    if isinstance(df, dict):
        return {"features": np.asarray(df["features"]),
                "labels": np.asarray(df["labels"])}
    if isinstance(df, (tuple, list)) and len(df) == 2:
        return {"features": np.asarray(df[0]), "labels": np.asarray(df[1])}
    # pyspark DataFrame? (duck-typed: has .toPandas and .sparkSession)
    if hasattr(df, "toPandas"):
        df = df.toPandas()
    # pandas DataFrame (duck-typed: has .loc and .columns)
    if hasattr(df, "columns") and hasattr(df, "loc"):
        if not feature_cols or not label_cols:
            raise ValueError("feature_cols and label_cols are required for "
                             "DataFrame input")
        feats = np.stack([np.stack(df[c].to_numpy())
                          for c in feature_cols], axis=-1)
        if feats.shape[-1] == 1:
            feats = feats[..., 0]
        labels = df[label_cols[0]].to_numpy() if len(label_cols) == 1 else \
            np.stack([df[c].to_numpy() for c in label_cols], axis=-1)
        return {"features": np.asarray(feats), "labels": np.asarray(labels)}
    raise TypeError(f"unsupported data input: {type(df).__name__}")


_CHUNK_ROWS = 65536   # flush threshold: the driver buffers at most this
                      # many rows per rank (+ one validation buffer)
_HASH_MULT = np.uint64(2654435761)  # Knuth multiplicative hash
_HASH_MASK = np.uint64(0xFFFFFFFF)


def _iter_chunks(df, feature_cols, label_cols, chunk_rows: int = _CHUNK_ROWS):
    """Yield normalized ``{'features','labels'}`` chunks WITHOUT
    materializing the dataset on the driver.

    The reference feeds workers from Petastorm shards written partition by
    partition (SURVEY.md 3.6); here the equivalents are:

    * pyspark DataFrame: rows stream through ``toLocalIterator()`` (the
      driver holds one partition at a time), buffered to ``chunk_rows``;
    * a generator/iterator of any supported item (dict of arrays,
      ``(x, y)`` tuple, pandas frame): each item is one chunk;
    * anything else (in-memory arrays / frames): one chunk -- the local
      fallback.
    """
    import itertools

    if hasattr(df, "toLocalIterator") and hasattr(df, "sparkSession"):
        rows = df.toLocalIterator()
        while True:
            buf = list(itertools.islice(rows, chunk_rows))
            if not buf:
                return
            import pandas as pd
            pdf = pd.DataFrame([r.asDict() for r in buf])
            yield _as_arrays(pdf, feature_cols, label_cols)
    elif hasattr(df, "__next__") or (
            hasattr(df, "__iter__")
            and not isinstance(df, (dict, tuple, list))
            and not hasattr(df, "shape") and not hasattr(df, "columns")):
        for item in df:
            yield _as_arrays(item, feature_cols, label_cols)
    else:
        yield _as_arrays(df, feature_cols, label_cols)


class _ShardWriter:
    """Streams row chunks into equal-length per-rank Store shards.

    Rows are assigned round-robin by train ordinal (balanced +
    shuffled-ish, like the old strided slice); every ``flush_rows`` rows a
    rank's buffer is written out as ``<train_path>.chunkNNNNN``, so driver
    memory is bounded by ``num_proc * flush_rows`` rows regardless of
    dataset size.  Validation rows are selected deterministically by a
    multiplicative hash of the global row index compared against the
    fraction -- any fraction is honored to ~1/2^32 resolution without
    driver-side shuffling or RNG state.

    Equal shard sizes are a CORRECTNESS requirement, not just balance:
    each worker's step count derives from its shard length, and a worker
    running one extra step would enter a collective its peers never join;
    :meth:`finish` trims the ragged tail (rewriting a flushed last chunk
    when necessary).
    """

    def __init__(self, store: Store, num_proc: int, val_fraction: float,
                 flush_rows: int = _CHUNK_ROWS):
        self.store = store
        self.num_proc = num_proc
        self.val_threshold = np.uint64(int(val_fraction * float(2 ** 32)))
        self.flush_rows = flush_rows
        self.seen = 0        # total rows
        self.train_seen = 0  # train ordinals handed out
        self.bufs = [{"features": [], "labels": []} for _ in range(num_proc)]
        self.buf_rows = [0] * num_proc
        self.chunk_seq = [0] * num_proc
        self.val_buf = {"features": [], "labels": []}
        self.val_rows = 0
        self.val_seq = 0
        self.written = [0] * num_proc  # rows flushed per rank

    def add(self, chunk: Dict[str, np.ndarray]) -> None:
        feats = np.asarray(chunk["features"])
        labels = np.asarray(chunk["labels"])
        n = len(feats)
        if len(labels) != n:
            raise ValueError(f"features ({n}) and labels ({len(labels)}) "
                             "row counts differ")
        idx = np.arange(self.seen, self.seen + n, dtype=np.uint64)
        val_mask = ((idx * _HASH_MULT) & _HASH_MASK) < self.val_threshold
        self.seen += n
        if val_mask.any():
            self.val_buf["features"].append(feats[val_mask])
            self.val_buf["labels"].append(labels[val_mask])
            self.val_rows += int(val_mask.sum())
            if self.val_rows >= self.flush_rows:
                self._flush_val()
        tf_, tl = feats[~val_mask], labels[~val_mask]
        nt = len(tf_)
        ranks = (self.train_seen + np.arange(nt)) % self.num_proc
        self.train_seen += nt
        for r in range(self.num_proc):
            sel = ranks == r
            if not sel.any():
                continue
            self.bufs[r]["features"].append(tf_[sel])
            self.bufs[r]["labels"].append(tl[sel])
            self.buf_rows[r] += int(sel.sum())
            if self.buf_rows[r] >= self.flush_rows:
                self._flush_rank(r)

    def _write_npz(self, path: str, feats, labels) -> None:
        _npz_write(self.store, path, feats, labels)

    def _flush_rank(self, r: int) -> None:
        if not self.buf_rows[r]:
            return
        path = (f"{self.store.get_train_data_path(r)}"
                f".chunk{self.chunk_seq[r]:05d}")
        self._write_npz(path, np.concatenate(self.bufs[r]["features"]),
                        np.concatenate(self.bufs[r]["labels"]))
        self.written[r] += self.buf_rows[r]
        self.chunk_seq[r] += 1
        self.bufs[r] = {"features": [], "labels": []}
        self.buf_rows[r] = 0

    def _flush_val(self) -> None:
        if not self.val_rows:
            return
        path = f"{self.store.get_val_data_path()}.chunk{self.val_seq:05d}"
        self._write_npz(path, np.concatenate(self.val_buf["features"]),
                        np.concatenate(self.val_buf["labels"]))
        self.val_seq += 1
        self.val_buf = {"features": [], "labels": []}
        self.val_rows = 0

    def finish(self) -> int:
        """Equalize shard lengths, flush remainders; returns val rows."""
        if self.train_seen < self.num_proc:
            raise ValueError(f"{self.train_seen} training rows < "
                             f"num_proc={self.num_proc}")
        target = self.train_seen // self.num_proc
        for r in range(self.num_proc):
            extra = self.written[r] + self.buf_rows[r] - target
            assert 0 <= extra <= 1, (r, extra)  # round-robin invariant
            if extra:
                if self.buf_rows[r]:
                    self.bufs[r]["features"][-1] = \
                        self.bufs[r]["features"][-1][:-1]
                    self.bufs[r]["labels"][-1] = \
                        self.bufs[r]["labels"][-1][:-1]
                    self.buf_rows[r] -= 1
                else:
                    # The extra row is already on disk: trim the last chunk.
                    path = (f"{self.store.get_train_data_path(r)}"
                            f".chunk{self.chunk_seq[r] - 1:05d}")
                    with np.load(io.BytesIO(self.store.read(path)),
                                 allow_pickle=False) as z:
                        self._write_npz(path, z["features"][:-1],
                                        z["labels"][:-1])
                    self.written[r] -= 1
            self._flush_rank(r)
        total_val = self.seen - self.train_seen
        self._flush_val()
        return total_val


def _npz_write(store: Store, path: str, feats, labels) -> None:
    buf = io.BytesIO()
    np.savez(buf, features=feats, labels=labels)
    store.write(path, buf.getvalue())


def _executor_partition_writer(store: Store, feature_cols, label_cols,
                               num_proc: int, val_threshold,
                               chunk_rows: int = _CHUNK_ROWS):
    """Build the ``mapPartitionsWithIndex`` task that writes one
    partition's rows straight to the Store from the executor.

    Rows are consumed in bounded sub-chunks (executor memory stays
    O(chunk_rows)); the validation stripe uses the same multiplicative
    hash as the driver path, keyed by a 64-bit (partition, ordinal)
    index so the split is deterministic without any global row count.
    Train rows round-robin over ranks with a per-partition rotating
    offset, so rank totals stay within one row per partition of equal.
    Yields ``(kind, rank, path, rows)`` records for the driver to
    aggregate and equalize.
    """

    def task(pid: int, rows):
        import itertools

        results = []
        seen = 0
        train_seen = 0
        sub = 0
        rows = iter(rows)
        while True:
            buf = list(itertools.islice(rows, chunk_rows))
            if not buf:
                break
            import pandas as pd
            pdf = pd.DataFrame(
                [r.asDict() if hasattr(r, "asDict") else r for r in buf])
            arrs = _as_arrays(pdf, feature_cols, label_cols)
            feats, labels = arrs["features"], arrs["labels"]
            n = len(feats)
            # Mix the partition id into the LOW 32 bits (a high shift
            # would vanish under the 32-bit mask, making every partition
            # reuse one per-ordinal pattern -- and always send ordinal 0
            # to validation).  Both constants are odd, so each term is a
            # bijection mod 2^32.
            ordinals = np.arange(seen + 1, seen + n + 1, dtype=np.uint64)
            h = (ordinals * _HASH_MULT
                 + np.uint64(pid) * np.uint64(2246822519)) & _HASH_MASK
            seen += n
            val_mask = h < val_threshold
            if val_mask.any():
                path = (f"{store.get_val_data_path()}"
                        f".chunk{pid:07d}_{sub:03d}")
                _npz_write(store, path, feats[val_mask], labels[val_mask])
                results.append(("val", -1, path, int(val_mask.sum())))
            tf_, tl = feats[~val_mask], labels[~val_mask]
            ranks = (pid + train_seen + np.arange(len(tf_))) % num_proc
            train_seen += len(tf_)
            for r in range(num_proc):
                sel = ranks == r
                if sel.any():
                    path = (f"{store.get_train_data_path(r)}"
                            f".chunk{pid:07d}_{sub:03d}")
                    _npz_write(store, path, tf_[sel], tl[sel])
                    results.append(("train", r, path, int(sel.sum())))
            sub += 1
        return iter(results)

    return task


def _trim_rank_to(store: Store, chunks: List, excess: int) -> None:
    """Drop ``excess`` rows from the END of a rank's chunk list (rewrite
    or delete tail chunks)."""
    while excess > 0:
        path, count = chunks[-1]
        if count <= excess:
            store.delete(path)
            chunks.pop()
            excess -= count
            continue
        with np.load(io.BytesIO(store.read(path)), allow_pickle=False) as z:
            _npz_write(store, path, z["features"][:-excess],
                       z["labels"][:-excess])
        chunks[-1] = (path, count - excess)
        excess = 0


def _write_shards_on_executors(store: Store, df, feature_cols, label_cols,
                               num_proc: int,
                               val_fraction: float) -> Optional[int]:
    """Materialize the rank shards FROM THE EXECUTORS, in parallel.

    Reference behavior (SURVEY.md 3.6): Petastorm materializes the
    DataFrame by writing parquet from the Spark workers; the driver never
    streams the rows.  Here each partition task writes its own Store
    chunks (requires ``store.executor_writable`` -- shared FS / object
    store) and returns (rank, rows) records; the driver only aggregates
    the records and trims tail chunks so every rank shard has EQUAL
    length (collective step counts must match across workers).

    Returns the validation row count, or ``None`` when the input is not
    an RDD-bearing DataFrame or the store is not executor-writable (the
    caller falls back to the streamed driver path).
    """
    rdd = getattr(df, "rdd", None)
    if rdd is None or not hasattr(rdd, "mapPartitionsWithIndex"):
        return None
    if not getattr(store, "executor_writable", False):
        return None
    _clean_intermediate(store, num_proc)
    thresh = np.uint64(int(val_fraction * float(2 ** 32)))
    task = _executor_partition_writer(store, feature_cols, label_cols,
                                      num_proc, thresh)
    records = list(rdd.mapPartitionsWithIndex(task).collect())
    # The chunks must be visible HERE for the trim (and for workers): a
    # non-shared filesystem (each executor's private /tmp) would
    # otherwise silently yield partial, unequal shards.  Fall back to the
    # driver-streamed path instead.
    missing = [path for _k, _r, path, _c in records
               if not store.exists(path)]
    if missing:
        import logging
        logging.getLogger(__name__).warning(
            "%d executor-written chunk(s) not visible from the driver "
            "(non-shared store path? e.g. %s); falling back to driver "
            "materialization", len(missing), missing[0])
        return None
    train_rows = [0] * num_proc
    val_rows = 0
    by_rank: Dict[int, List] = {r: [] for r in range(num_proc)}
    for kind, r, path, count in records:
        if kind == "val":
            val_rows += count
        else:
            train_rows[r] += count
            by_rank[r].append((path, count))
    total = sum(train_rows)
    if total < num_proc:
        raise ValueError(f"{total} training rows < num_proc={num_proc}")
    # Equal shard lengths are a correctness requirement (step counts
    # derive from shard length); trim every rank to the smallest -- the
    # per-partition rotating round-robin bounds the loss to at most one
    # row per partition per rank.
    target = min(train_rows)
    if target == 0:
        # Possible with more ranks than rows-per-partition spread; an
        # empty shard would crash its worker, and trimming everyone to
        # zero destroys the dataset.
        raise ValueError(
            f"executor materialization left rank(s) with zero rows "
            f"(per-rank counts {train_rows}); use fewer workers or "
            f"repartition the DataFrame")
    for r in range(num_proc):
        by_rank[r].sort(key=lambda pc: pc[0])
        _trim_rank_to(store, by_rank[r], train_rows[r] - target)
    return val_rows


def _clean_intermediate(store: Store, num_proc: int) -> None:
    """Remove stale chunk files from a previous fit on the same store."""
    for r in range(num_proc):
        for p in store.list_prefix(f"{store.get_train_data_path(r)}.chunk"):
            store.delete(p)
        if store.exists(store.get_train_data_path(r)):
            store.delete(store.get_train_data_path(r))
    for p in store.list_prefix(f"{store.get_val_data_path()}.chunk"):
        store.delete(p)
    if store.exists(store.get_val_data_path()):
        store.delete(store.get_val_data_path())


def _write_shards(store: Store, chunks, num_proc: int,
                  val_fraction: float) -> int:
    """Stream chunks into the store's rank-sharded intermediate layout.

    Returns the number of validation rows held out.
    """
    _clean_intermediate(store, num_proc)
    w = _ShardWriter(store, num_proc, val_fraction)
    for chunk in chunks:
        w.add(chunk)
    return w.finish()


def _orderly_teardown(hvd) -> None:
    """Tear the comm plane down without tripping the peers' error polling.

    Rank 0's process hosts the JAX coordination service; if it stops (or
    its process exits) while another worker's client is still connected,
    that worker's poll-for-error thread LOG(FATAL)s the process (SIGABRT)
    and its Gloo peers see connection resets.  So: barrier to align
    everyone past the last collective, disconnect non-owner clients first,
    and only then let rank 0 stop the service.
    """
    import time

    hvd.barrier()
    if hvd.rank() == 0:
        time.sleep(1.5)  # let non-owner clients disconnect first
    hvd.shutdown()


def _shard_chunk_paths(store: Store, base: str) -> List[str]:
    paths = store.list_prefix(f"{base}.chunk")
    if not paths:
        if not store.exists(base):
            raise FileNotFoundError(f"no shard data under {base}")
        paths = [base]
    return paths


def _load_shard(store: Store, base: str) -> Dict[str, np.ndarray]:
    """Load one rank's WHOLE shard into memory (chunked layout or a bare
    ``<base>`` file).  Used by the torch/keras workers, whose training
    loops index the shard randomly; the JAX worker streams batches through
    :func:`_iter_shard_batches` instead and stays out-of-core end to end."""
    feats, labels = [], []
    for p in _shard_chunk_paths(store, base):
        with np.load(io.BytesIO(store.read(p)), allow_pickle=False) as z:
            feats.append(z["features"])
            labels.append(z["labels"])
    return {"features": np.concatenate(feats),
            "labels": np.concatenate(labels)}


def _shard_row_count(store: Store, base: str) -> int:
    total = 0
    for p in _shard_chunk_paths(store, base):
        with np.load(io.BytesIO(store.read(p)), allow_pickle=False) as z:
            total += int(z["labels"].shape[0])
    return total


def _iter_shard_batches(store: Store, base: str, bs: int):
    """Stream ``(features, labels)`` batches of exactly ``bs`` rows from a
    chunked shard, holding at most one chunk + ``bs`` rows in memory.

    The tail (< bs rows) is dropped; equal shard lengths make the drop
    identical across ranks, keeping collective step counts aligned.
    """
    fb, lb, have = [], [], 0
    for p in _shard_chunk_paths(store, base):
        with np.load(io.BytesIO(store.read(p)), allow_pickle=False) as z:
            fb.append(z["features"])
            lb.append(z["labels"])
            have += len(lb[-1])
        if have >= bs:
            f, l = np.concatenate(fb), np.concatenate(lb)
            n_full = (have // bs) * bs
            for i in range(0, n_full, bs):
                yield f[i:i + bs], l[i:i + bs]
            fb, lb, have = [f[n_full:]], [l[n_full:]], have - n_full


# ---------------------------------------------------------------------------
# estimator base
# ---------------------------------------------------------------------------

@dataclass
class EstimatorParams:
    """Common estimator parameters (reference ``common/params.py``)."""

    num_proc: int = 1
    batch_size: int = 32
    epochs: int = 1
    store: Optional[Store] = None
    feature_cols: Optional[List[str]] = None
    label_cols: Optional[List[str]] = None
    validation: float = 0.0  # fraction of rows held out
    run_id: Optional[str] = None
    verbose: int = 1
    backend: str = "local"  # "local" (spawned procs) or "spark" (barrier)
    # Write intermediate shards from the Spark executors (Petastorm-style
    # parallel materialization) when the input has an RDD and the store is
    # executor-writable; falls back to the streamed driver path otherwise.
    materialize_on_executors: bool = True


class _EstimatorBase:
    def __init__(self, **kwargs):
        self.params = EstimatorParams(**{
            k: v for k, v in kwargs.items()
            if k in EstimatorParams.__dataclass_fields__})

    # subclasses define: _make_worker_spec(), _worker_fn, _make_model()

    def fit(self, df) -> Any:
        p = self.params
        store = p.store or LocalStore(os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "hvd_tpu_estimator"))
        run_id = p.run_id or f"run_{uuid.uuid4().hex[:8]}"
        val_rows = None
        if p.materialize_on_executors:
            try:
                val_rows = _write_shards_on_executors(
                    store, df, p.feature_cols, p.label_cols, p.num_proc,
                    p.validation)
            except ValueError:
                raise           # too few rows: not a fallback situation
            except Exception:
                import logging
                logging.getLogger(__name__).warning(
                    "executor-parallel materialization failed; falling "
                    "back to the streamed driver path", exc_info=True)
                val_rows = None
        if val_rows is None:
            chunks = _iter_chunks(df, p.feature_cols, p.label_cols)
            _write_shards(store, chunks, p.num_proc, p.validation)
        spec = dict(self._make_worker_spec(),
                    store_prefix=store.prefix_path,
                    run_id=run_id, num_proc=p.num_proc,
                    batch_size=p.batch_size, epochs=p.epochs,
                    verbose=p.verbose)
        if p.backend == "spark":
            from . import run as spark_run
            histories = spark_run(type(self)._worker_fn, args=(spec,),
                                  num_proc=p.num_proc)
        else:
            from ..ray import RayExecutor
            ex = RayExecutor(num_workers=p.num_proc, cpu=True, use_ray=False)
            ex.start()
            try:
                histories = ex.run(type(self)._worker_fn, args=(spec,))
            finally:
                ex.shutdown()
        ckpt = store.read(store.get_checkpoint_path(run_id))
        return self._make_model(ckpt, histories[0])


# ---------------------------------------------------------------------------
# JAX estimator (flagship)
# ---------------------------------------------------------------------------

def _jax_worker(spec) -> List[float]:
    """Per-worker training loop: runs in a spawned process with the
    ``HOROVOD_*`` identity env already exported by the executor.

    Rides the standard machinery end-to-end: ``DistributedOptimizer``
    (fused psum), ``make_flax_train_step`` (BN stat sync), and
    ``shard_batch_from_local`` (each rank feeds its own shard, the
    reference's per-rank reader model).  Batches STREAM from the chunked
    shard (one chunk in memory at a time) -- with the driver-side
    streamed materialization this keeps the whole path out-of-core, the
    Petastorm-equivalent property.
    """
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd

    hvd.init()
    store = LocalStore(spec["store_prefix"])
    base = store.get_train_data_path(hvd.rank())
    n = _shard_row_count(store, base)
    model = pickle.loads(spec["model"])
    opt = hvd.DistributedOptimizer(
        optax.adam(spec["lr"]) if spec["opt"] == "adam"
        else optax.sgd(spec["lr"], momentum=0.9))

    x1, _y1 = next(_iter_shard_batches(store, base, 1))
    x0 = jnp.asarray(x1, jnp.float32)
    # PRNGKey(0) init is deterministic, so every rank starts from identical
    # params (the broadcast_parameters step is a no-op by construction).
    variables = model.init(jax.random.PRNGKey(0), x0, train=False)
    params = hvd.replicate(variables["params"])
    stats = hvd.replicate(variables.get("batch_stats", {}))
    opt_state = hvd.replicate(opt.init(params))

    if spec["loss"] == "mse":
        label_dtype = np.float32

        def loss_fn(logits, y):
            if logits.ndim > y.ndim:
                logits = jnp.squeeze(logits, -1)
            return jnp.mean((logits - y) ** 2)
    else:
        label_dtype = np.int32
        loss_fn = None  # default: softmax xent with integer labels

    from ..training import make_flax_train_step
    step = make_flax_train_step(model.apply, opt, loss_fn=loss_fn)

    bs = max(1, min(spec["batch_size"], n))
    history = []
    for _ in range(spec["epochs"]):
        ep = []
        for xb, yb in _iter_shard_batches(store, base, bs):
            batch = hvd.shard_batch_from_local(
                (np.asarray(xb, np.float32), np.asarray(yb, label_dtype)))
            params, stats, opt_state, loss = step(params, stats, opt_state,
                                                  batch)
            ep.append(float(loss))
        history.append(float(np.mean(ep)))
    if hvd.rank() == 0:
        buf = io.BytesIO()
        flat = {f"p/{jax.tree_util.keystr(kp)}": np.asarray(v)
                for kp, v in
                jax.tree_util.tree_flatten_with_path(params)[0]}
        flat.update({f"s/{jax.tree_util.keystr(kp)}": np.asarray(v)
                     for kp, v in
                     jax.tree_util.tree_flatten_with_path(stats)[0]})
        np.savez(buf, **flat)
        store.write(store.get_checkpoint_path(spec["run_id"]),
                    buf.getvalue())
    _orderly_teardown(hvd)
    return history


class JaxEstimator(_EstimatorBase):
    """Train a flax module across ``num_proc`` workers.

    ``loss`` is ``"xent"`` (integer labels) or ``"mse"``; custom losses
    belong in a hand-written worker (this mirrors the reference, whose
    estimators also accept only framework-standard losses).
    """

    def __init__(self, model, loss: str = "xent", lr: float = 1e-3,
                 optimizer: str = "adam", **kwargs):
        super().__init__(**kwargs)
        self.model = model
        self.loss = loss
        self.lr = lr
        self.optimizer = optimizer

    _worker_fn = staticmethod(_jax_worker)

    def _make_worker_spec(self) -> dict:
        return {"model": pickle.dumps(self.model), "loss": self.loss,
                "lr": self.lr, "opt": self.optimizer}

    def _make_model(self, ckpt: bytes, history) -> "JaxModel":
        return JaxModel(self.model, ckpt, history)


def _extract_features(df, feature_cols=None) -> np.ndarray:
    """Feature matrix from a DataFrame / dict / raw array."""
    if hasattr(df, "columns") and hasattr(df, "loc"):
        cols = feature_cols or list(df.columns)
        x = np.stack([np.stack(df[c].to_numpy()) for c in cols], axis=-1)
        return x[..., 0] if x.shape[-1] == 1 else x
    if isinstance(df, dict):
        return np.asarray(df["features"])
    return np.asarray(df)


class JaxModel:
    """Fitted transformer: applies the trained flax module."""

    def __init__(self, module, ckpt: bytes, history):
        self.module = module
        self.history = history
        with np.load(io.BytesIO(ckpt)) as z:
            self._flat = {k: z[k] for k in z.files}
        self._variables = None

    def _restore(self, x):
        import jax
        import jax.numpy as jnp

        v = self.module.init(jax.random.PRNGKey(0),
                             jnp.asarray(x[:1], jnp.float32), train=False)

        def fill(prefix, tree):
            flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
            return jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(self._flat[
                    f"{prefix}/{jax.tree_util.keystr(kp)}"])
                    for kp, _ in flat])

        out = {"params": fill("p", v["params"])}
        if "batch_stats" in v:
            out["batch_stats"] = fill("s", v["batch_stats"])
        return out

    def transform(self, df, feature_cols=None):
        x = _extract_features(df, feature_cols)
        if self._variables is None:
            self._variables = self._restore(x)
        import jax.numpy as jnp
        return np.asarray(self.module.apply(self._variables,
                                            jnp.asarray(x, jnp.float32),
                                            train=False))

    predict = transform


# ---------------------------------------------------------------------------
# Torch estimator (rides horovod_tpu.torch shim)
# ---------------------------------------------------------------------------

def _run_torch_training(spec, make_optimizer, compute_loss,
                        float_labels: Optional[bool]) -> List[float]:
    """Shared torch-shim worker scaffold: init + shard load + broadcast,
    the distributed batch loop, rank-0 checkpoint through the Store, and
    orderly teardown.  ``make_optimizer(model)`` sources the base
    optimizer; ``compute_loss(model, xb, yb, batch_idx)`` returns the
    per-batch loss tensor.
    """
    import torch

    import horovod_tpu.torch as hvd

    hvd.init()
    store = LocalStore(spec["store_prefix"])
    shard = _load_shard(store, store.get_train_data_path(hvd.rank()))
    model = pickle.loads(spec["model"])
    model.train()
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvd.DistributedOptimizer(
        make_optimizer(model), named_parameters=model.named_parameters())

    x = torch.as_tensor(shard["features"], dtype=torch.float32)
    y = torch.as_tensor(shard["labels"])
    if float_labels is None:  # infer: float labels stay, others are classes
        float_labels = y.dtype.is_floating_point
    if not float_labels:
        y = y.long()
    n, bs = len(x), max(1, min(spec["batch_size"], len(x)))
    history = []
    for _ in range(spec["epochs"]):
        ep = []
        for bi, i in enumerate(range(0, n - bs + 1, bs)):
            opt.zero_grad()
            loss = compute_loss(model, x[i:i + bs], y[i:i + bs], bi)
            loss.backward()
            opt.step()
            ep.append(float(loss))
        history.append(float(np.mean(ep)))
    if hvd.rank() == 0:
        buf = io.BytesIO()
        torch.save({"model": model, "state_dict": model.state_dict()}, buf)
        store.write(store.get_checkpoint_path(spec["run_id"]),
                    buf.getvalue())
    _orderly_teardown(hvd)
    return history


def _torch_worker(spec) -> List[float]:
    import torch

    def make_optimizer(model):
        if spec["opt"] == "sgd":
            return torch.optim.SGD(model.parameters(), lr=spec["lr"],
                                   momentum=0.9)
        return torch.optim.Adam(model.parameters(), lr=spec["lr"])

    mse = spec["loss"] == "mse"
    loss_fn = torch.nn.MSELoss() if mse else torch.nn.CrossEntropyLoss()

    def compute_loss(model, xb, yb, bi):
        out = model(xb)
        return loss_fn(out.squeeze() if mse else out, yb)

    return _run_torch_training(spec, make_optimizer, compute_loss,
                               float_labels=mse)


class TorchEstimator(_EstimatorBase):
    """Reference ``horovod.spark.torch.TorchEstimator`` parity: trains a
    ``torch.nn.Module`` with the torch API shim's DistributedOptimizer
    (gradients reduced through the XLA collective layer)."""

    def __init__(self, model, loss: str = "xent", lr: float = 1e-3,
                 optimizer: str = "adam", **kwargs):
        super().__init__(**kwargs)
        self.model = model
        self.loss = loss
        self.lr = lr
        self.optimizer = optimizer

    _worker_fn = staticmethod(_torch_worker)

    def _make_worker_spec(self) -> dict:
        return {"model": pickle.dumps(self.model), "loss": self.loss,
                "lr": self.lr, "opt": self.optimizer}

    def _make_model(self, ckpt: bytes, history) -> "TorchModel":
        return TorchModel(ckpt, history)


class TorchModel:
    def __init__(self, ckpt: bytes, history):
        import torch

        payload = torch.load(io.BytesIO(ckpt), weights_only=False)
        self.model = payload["model"]
        self.model.load_state_dict(payload["state_dict"])
        self.model.eval()
        self.history = history

    def transform(self, df, feature_cols=None):
        import torch

        x = _extract_features(df, feature_cols)
        with torch.no_grad():
            return self.model(
                torch.as_tensor(x, dtype=torch.float32)).numpy()

    predict = transform


# ---------------------------------------------------------------------------
# Lightning estimator (LightningModule protocol over the torch shim)
# ---------------------------------------------------------------------------

def _first_optimizer(cfg):
    """``configure_optimizers()`` -> the (single) optimizer to drive.

    Accepts the LightningModule return shapes: an optimizer, a list/tuple
    of optimizers (optionally paired with schedulers), or a dict with an
    ``"optimizer"`` key.
    """
    if isinstance(cfg, dict):
        return cfg["optimizer"]
    if isinstance(cfg, (list, tuple)):
        head = cfg[0]
        if isinstance(head, (list, tuple)):  # ([opts], [scheds])
            return head[0]
        return _first_optimizer(head) if isinstance(head, dict) else head
    return cfg


def _lightning_worker(spec) -> List[float]:
    """Mini Trainer loop speaking the LightningModule protocol:
    ``configure_optimizers`` -> DistributedOptimizer wrap,
    ``training_step((x, y), i)`` -> backward -> step.  Works with real
    ``pytorch_lightning.LightningModule`` objects and with any
    ``torch.nn.Module`` implementing the two methods.
    """
    def make_optimizer(model):
        return _first_optimizer(model.configure_optimizers())

    def compute_loss(model, xb, yb, bi):
        out = model.training_step((xb, yb), bi)
        return out["loss"] if isinstance(out, dict) else out

    return _run_torch_training(spec, make_optimizer, compute_loss,
                               float_labels=None)


class LightningEstimator(_EstimatorBase):
    """Reference ``horovod.spark.lightning.TorchEstimator`` parity: trains
    a LightningModule-protocol model (``training_step`` +
    ``configure_optimizers``) across workers with the torch shim's
    DistributedOptimizer.  ``pytorch_lightning`` itself is optional — the
    worker drives the protocol directly, so plain modules implementing it
    work too."""

    def __init__(self, model, **kwargs):
        super().__init__(**kwargs)
        if not (callable(getattr(model, "training_step", None))
                and callable(getattr(model, "configure_optimizers", None))):
            raise TypeError(
                "LightningEstimator needs a model implementing "
                "training_step(batch, batch_idx) and "
                "configure_optimizers() (a pytorch_lightning."
                "LightningModule, or any torch.nn.Module with those "
                "methods)")
        self.model = model

    _worker_fn = staticmethod(_lightning_worker)

    def _make_worker_spec(self) -> dict:
        return {"model": pickle.dumps(self.model)}

    def _make_model(self, ckpt: bytes, history) -> "TorchModel":
        return TorchModel(ckpt, history)


# ---------------------------------------------------------------------------
# Keras estimator (rides horovod_tpu.keras shim)
# ---------------------------------------------------------------------------

def _keras_worker(spec) -> List[float]:
    import tensorflow as tf

    import horovod_tpu.keras as hvd

    hvd.init()
    store = LocalStore(spec["store_prefix"])
    shard = _load_shard(store, store.get_train_data_path(hvd.rank()))
    model = tf.keras.models.model_from_json(spec["model_json"])
    weights = pickle.loads(spec["weights"])
    if weights is not None:
        model.set_weights(weights)
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.Adam(spec["lr"]) if spec["opt"] == "adam"
        else tf.keras.optimizers.SGD(spec["lr"], momentum=0.9))
    model.compile(optimizer=opt, loss=spec["loss"])
    callbacks = [hvd.BroadcastGlobalVariablesCallback(0),
                 hvd.MetricAverageCallback()]
    hist = model.fit(shard["features"], shard["labels"],
                     batch_size=spec["batch_size"], epochs=spec["epochs"],
                     verbose=0, callbacks=callbacks)
    if hvd.rank() == 0:
        # Re-use the pre-compile architecture json: the compiled model's
        # to_json() embeds the Distributed optimizer wrapper in its compile
        # config, which model_from_json cannot deserialize on the driver.
        store.write(store.get_checkpoint_path(spec["run_id"]),
                    pickle.dumps({"json": spec["model_json"],
                                  "weights": model.get_weights()}))
    _orderly_teardown(hvd)
    return [float(v) for v in hist.history["loss"]]


class KerasEstimator(_EstimatorBase):
    """Reference ``horovod.spark.keras.KerasEstimator`` parity: Keras model
    trained under the keras shim (DistributedOptimizer + broadcast/metric
    callbacks).  ``loss`` is any keras-serializable loss name."""

    def __init__(self, model, loss: str = "sparse_categorical_crossentropy",
                 lr: float = 1e-3, optimizer: str = "adam", **kwargs):
        super().__init__(**kwargs)
        self.model = model
        self.loss = loss
        self.lr = lr
        self.optimizer = optimizer

    _worker_fn = staticmethod(_keras_worker)

    def _make_worker_spec(self) -> dict:
        return {"model_json": self.model.to_json(),
                "weights": pickle.dumps(self.model.get_weights()
                                        if self.model.built else None),
                "loss": self.loss, "lr": self.lr, "opt": self.optimizer}

    def _make_model(self, ckpt: bytes, history) -> "KerasModel":
        return KerasModel(ckpt, history)


class KerasModel:
    def __init__(self, ckpt: bytes, history):
        import tensorflow as tf

        payload = pickle.loads(ckpt)
        self.model = tf.keras.models.model_from_json(payload["json"])
        self.model.set_weights(payload["weights"])
        self.history = history

    def transform(self, df, feature_cols=None):
        x = _extract_features(df, feature_cols)
        return self.model.predict(x, verbose=0)

    predict = transform
