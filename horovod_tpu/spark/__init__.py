"""``horovod_tpu.spark``: Spark cluster integration (reference
``horovod/spark/`` parity surface).

``run(fn)`` executes ``fn`` once per worker inside a Spark barrier-mode
stage, with the ``HOROVOD_*`` identity env and the coordinator address
injected exactly like ``horovod_tpu.run`` does for local workers (the
reference's ``horovod.spark.run`` + ``gloo_run`` path, SURVEY.md section
3.6).  PySpark is an optional dependency: importing this package works
without it; calling :func:`run` raises with guidance.

The :class:`~horovod_tpu.spark.store.LocalStore` / ``Store`` abstraction
(checkpoint + intermediate-data layout used by the estimators) is
dependency-free and fully functional.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, List, Optional

from .store import LocalStore, Store  # noqa: F401
from .estimator import (  # noqa: F401
    EstimatorParams, JaxEstimator, JaxModel, KerasEstimator, KerasModel,
    LightningEstimator, TorchEstimator, TorchModel,
)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark.run() requires pyspark, which is not "
            "installed in this environment. Install pyspark (or launch "
            "workers directly with `python -m horovod_tpu.run -np N ...`)."
        ) from e


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None, verbose: int = 1) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark barrier tasks.

    Each task initializes the framework with its barrier partition id as
    rank; rank 0's host serves as the JAX coordinator (the rendezvous
    analogue).  Returns the per-rank results, rank-ordered.
    """
    pyspark = _require_pyspark()
    kwargs = kwargs or {}
    spark = pyspark.sql.SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    n = num_proc or int(sc.defaultParallelism)

    coordinator_port = _free_port()

    def task_fn(iterator):
        from pyspark import BarrierTaskContext
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        infos = ctx.getTaskInfos()
        coordinator = infos[0].address.split(":")[0]
        os.environ.update(task_env(rank, n, coordinator, coordinator_port))
        ctx.barrier()
        yield rank, fn(*args, **kwargs)

    results = (sc.parallelize(range(n), n)
               .barrier()
               .mapPartitions(task_fn)
               .collect())
    return [r for _, r in sorted(results)]


def task_env(rank: int, size: int, coordinator: str, port: int) -> dict:
    """The env a Spark barrier task exports before user code runs
    (mirrors ``horovod_tpu.run.launch.worker_env``; dependency-free so the
    layout is unit-testable without a cluster)."""
    from ..run.launch import worker_env
    return worker_env(rank=rank, size=size, coordinator=coordinator,
                      port=port, cpu=False)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]
