"""Estimator storage abstraction (``horovod/spark/common/store.py``
parity).

A ``Store`` names the directory layout the Spark estimators use for
checkpoints, logs and intermediate (Petastorm-style) training data.  The
local-filesystem implementation is complete; HDFS/S3 flavours of the
reference require their respective clients and raise with guidance.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional


class Store:
    """Abstract storage layout: run-scoped checkpoint/log/data prefixes."""

    #: True when executor/worker processes can write the store's paths
    #: directly (shared filesystem, object store) -- enables the
    #: executor-parallel shard materialization (SURVEY.md 3.6: Petastorm
    #: writes shards from Spark workers, not through the driver).
    executor_writable = False

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path

    # -- layout -----------------------------------------------------------
    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, "runs", run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        p = os.path.join(self.prefix_path, "intermediate_train_data")
        return p if idx is None else f"{p}.{idx}"

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        p = os.path.join(self.prefix_path, "intermediate_val_data")
        return p if idx is None else f"{p}.{idx}"

    # -- IO (subclasses implement) ---------------------------------------
    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def list_prefix(self, prefix: str):
        """Paths in the store starting with ``prefix``, sorted.  Used by the
        chunked intermediate-data layout (shards stream in as
        ``<base>.chunk00000``, ``.chunk00001``, ...)."""
        raise NotImplementedError

    @classmethod
    def create(cls, prefix_path: str) -> "Store":
        """Pick a store flavour from the path scheme (reference
        ``Store.create`` behavior)."""
        if prefix_path.startswith(("hdfs://", "webhdfs://")):
            return HDFSStore(prefix_path)
        if prefix_path.startswith(("s3://", "gs://")):
            raise ValueError(
                f"object-store paths need a fuse mount or client; got "
                f"{prefix_path!r}. Mount it and pass the local mount path.")
        return LocalStore(prefix_path)


class LocalStore(Store):
    """Local-filesystem store (the reference's ``FilesystemStore``).

    ``executor_writable`` assumes the path is reachable from every
    executor -- true for local-mode Spark and for NFS-style shared
    mounts, the same assumption the reference's FilesystemStore makes.
    """

    executor_writable = True

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def list_prefix(self, prefix: str):
        import glob
        return sorted(glob.glob(glob.escape(prefix) + "*"))


class HDFSStore(Store):
    def __init__(self, prefix_path: str):
        raise ImportError(
            "HDFSStore requires an hdfs client (pyarrow.fs or hdfs3), "
            "not installed in this environment; use LocalStore on a "
            "mounted path instead.")
