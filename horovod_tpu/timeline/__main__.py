"""Offline cross-rank timeline merge + straggler report.

Usage::

    python -m horovod_tpu.timeline --merge <dir> [--out merged.json]

``<dir>`` holds one Chrome-trace JSON per rank (each written by
:class:`~horovod_tpu.timeline.Timeline`, which stamps a ``clock_anchor``
metadata event -- ``epoch_unix_us``, ``rank``, ``hostname`` -- at open).
The merge aligns every file onto the lowest rank's clock via the
anchors (no live KV handshake needed), assigns ONE pid per rank (the
original per-track pids become tids), and writes a single
Perfetto-loadable JSON.

It then prints the straggler/critical-path report: per-rank host-time
attribution across compute / exchange / fence / dispatch-gap span
categories, and the :class:`~horovod_tpu.timeline.straggler.
StragglerMonitor` verdict over the per-step span summaries recovered
from the tagged events.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from .straggler import StragglerMonitor

#: Span/phase name -> attribution category.  Eager phases are upper-case
#: (ALLREDUCE, NEGOTIATE_*...), span-layer kinds lower-case.
_CATEGORIES = (
    ("fence", ("fence", "FENCE")),
    ("exchange", ("exchange", "bucket")),
    ("negotiate", ("negotiate",)),
    ("dispatch_gap", ("dispatch_gap",)),
    ("compute", ("dispatch", "compute")),
)


def classify(name: str) -> str:
    for cat, names in _CATEGORIES:
        if name in names:
            return cat
    if name.startswith("NEGOTIATE_"):
        return "negotiate"
    if name.isupper():  # eager collective execution phases
        return "exchange"
    return "compute"


#: Dominant category -> the report's "-bound" label.
_BOUND = {"compute": "compute-bound", "exchange": "exchange-bound",
          "negotiate": "exchange-bound", "fence": "fence-bound",
          "dispatch_gap": "host-bound (late dispatch / input pipeline)"}


def load_trace(path: str) -> Tuple[Optional[dict], List[dict]]:
    """``(clock_anchor_args_or_None, events)`` for one trace file."""
    with open(path) as f:
        events = json.load(f)
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome-trace event array")
    anchor = None
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "clock_anchor":
            anchor = ev.get("args") or {}
            break
    return anchor, events


def _pair_durations(events: List[dict]) -> Dict[Tuple[int, str], Dict[str, float]]:
    """Recover per-(step, category) host seconds from B/E pairs.
    Events whose args carry no step aggregate under step -1."""
    stacks: Dict[Tuple, List[Tuple[str, float, dict]]] = {}
    out: Dict[Tuple[int, str], Dict[str, float]] = {}
    for ev in events:
        ph = ev.get("ph")
        if ph == "X":  # retroactive complete event (dispatch gap)
            args = ev.get("args") or {}
            step = int(args.get("step", -1))
            cat = classify(ev.get("name", ""))
            bucket = out.setdefault((step, cat), {})
            bucket["secs"] = bucket.get("secs", 0.0) + \
                max(0.0, float(ev.get("dur", 0.0))) / 1e6
            continue
        if ph not in ("B", "E"):
            continue
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(key, []).append(
                (ev["name"], float(ev["ts"]), ev.get("args") or {}))
            continue
        stack = stacks.get(key) or []
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == ev["name"]:
                name, ts0, args = stack.pop(i)
                step = int(args.get("step", -1))
                cat = classify(name)
                bucket = out.setdefault((step, cat), {})
                bucket["secs"] = bucket.get("secs", 0.0) + \
                    max(0.0, float(ev["ts"]) - ts0) / 1e6
                break
    return out


def merge(trace_dir: str, out_path: Optional[str] = None) -> dict:
    """Merge every per-rank trace under ``trace_dir``; returns the report
    dict (also printed by :func:`main`)."""
    paths = sorted(glob.glob(os.path.join(trace_dir, "*.json")))
    ranks: List[Tuple[int, dict, List[dict], str]] = []
    skipped = []
    for p in paths:
        if out_path and os.path.abspath(p) == os.path.abspath(out_path):
            continue
        try:
            anchor, events = load_trace(p)
        except (ValueError, json.JSONDecodeError, OSError) as e:
            skipped.append((p, str(e)))
            continue
        if anchor is None:
            skipped.append((p, "no clock_anchor metadata (pre-merge-era "
                               "trace?)"))
            continue
        ranks.append((int(anchor.get("rank", len(ranks))), anchor,
                      events, p))
    if not ranks:
        raise SystemExit(
            f"no mergeable traces under {trace_dir!r} "
            f"({len(skipped)} file(s) skipped)")
    ranks.sort(key=lambda t: t[0])
    ref_rank, ref_anchor = ranks[0][0], ranks[0][1]
    ref_epoch = float(ref_anchor["epoch_unix_us"])

    merged: List[dict] = []
    per_rank: Dict[int, dict] = {}
    monitor = StragglerMonitor(world=len(ranks), stall_check_time=0.0)
    for rank, anchor, events, path in ranks:
        offset_us = float(anchor["epoch_unix_us"]) - ref_epoch
        pid = rank + 1
        track_names: Dict[int, str] = {}
        first_ts = last_ts = None
        merged.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": f"rank {rank} "
                             f"({anchor.get('hostname', '?')})"}})
        for ev in events:
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    track_names[ev.get("pid")] = \
                        (ev.get("args") or {}).get("name", "")
                continue
            ts = float(ev.get("ts", 0.0)) + offset_us
            if first_ts is None or ts < first_ts:
                first_ts = ts
            if last_ts is None or ts > last_ts:
                last_ts = ts
            nev = dict(ev)
            nev["ts"] = ts
            nev["tid"] = ev.get("pid", 0)  # track -> thread
            nev["pid"] = pid               # ONE pid per rank
            merged.append(nev)
        for tid, tname in track_names.items():
            merged.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        durs = _pair_durations(events)
        cats: Dict[str, float] = {}
        steps: Dict[int, Dict[str, float]] = {}
        for (step, cat), d in durs.items():
            cats[cat] = cats.get(cat, 0.0) + d["secs"]
            if step >= 0:
                steps.setdefault(step, {})[cat] = \
                    steps.get(step, {}).get(cat, 0.0) + d["secs"]
        wall = ((last_ts - first_ts) / 1e6
                if first_ts is not None and last_ts is not None else 0.0)
        per_rank[rank] = {"categories": cats, "wall_s": wall,
                          "path": path, "steps": len(steps)}
        for step, kinds in sorted(steps.items()):
            monitor.observe({
                "rank": rank, "step": step,
                "t0_us": float(anchor["epoch_unix_us"]),
                "wall_s": sum(kinds.values()),
                "spans": kinds})

    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    rep = monitor.report()
    return {"ranks": len(ranks), "events": len(merged),
            "out": out_path, "skipped": skipped,
            "per_rank": per_rank, "straggler": rep,
            "render": monitor.render()}


def _print_report(rep: dict) -> None:
    print(f"merged {rep['ranks']} rank trace(s), "
          f"{rep['events']} events -> {rep['out']}")
    for p, why in rep["skipped"]:
        print(f"  skipped {p}: {why}")
    print("\nper-rank host-time attribution:")
    for rank in sorted(rep["per_rank"]):
        info = rep["per_rank"][rank]
        cats = info["categories"]
        total = sum(cats.values()) or 1.0
        parts = "  ".join(
            f"{c} {100.0 * s / total:5.1f}%"
            for c, s in sorted(cats.items(), key=lambda kv: -kv[1]))
        dominant = max(cats, key=cats.get) if cats else "compute"
        print(f"  rank {rank}: busy {total:8.4f}s  {parts}  -> "
              f"{_BOUND.get(dominant, 'compute-bound')}")
    print()
    print(rep["render"])


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.timeline",
        description="merge per-rank timeline JSONs and report stragglers")
    p.add_argument("--merge", metavar="DIR", required=True,
                   help="directory of per-rank Chrome-trace JSON files")
    p.add_argument("--out", metavar="PATH", default=None,
                   help="merged trace output "
                        "(default: <DIR>/merged_timeline.json)")
    args = p.parse_args(argv)
    out = args.out or os.path.join(args.merge, "merged_timeline.json")
    rep = merge(args.merge, out)
    _print_report(rep)
    return 0


if __name__ == "__main__":
    sys.exit(main())
