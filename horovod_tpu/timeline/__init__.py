"""Chrome-tracing timeline (``HOROVOD_TIMELINE`` parity).

Analogue of the reference's ``horovod/common/timeline.cc``: a JSON writer
producing ``chrome://tracing`` / Perfetto-loadable output with per-tensor
phase events.  The reference's phases (NEGOTIATE_ALLREDUCE, QUEUE,
MEMCPY_IN_FUSION_BUFFER, NCCL_ALLREDUCE, MEMCPY_OUT_FUSION_BUFFER) map to
this runtime's phases: NEGOTIATE_* = trace+compile (executable-cache miss),
CACHE_HIT, and the collective execution itself.  Device-side timing is the
profiler's job (``jax.profiler`` emits XPlane/Perfetto); this timeline
captures the *semantic* host-side lifecycle, as SURVEY.md section 5.1
prescribes.

Events are buffered and flushed by a writer thread like the reference's,
so the hot path only appends to a deque.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional


class Timeline:
    """Append-only Chrome-trace event stream with a background writer."""

    def __init__(self, path: str, mark_cycles: bool = False,
                 flush_interval: float = 1.0, rank: Optional[int] = None,
                 hostname: Optional[str] = None):
        self.path = path
        self.mark_cycles = mark_cycles
        self._events: Deque[dict] = deque()
        self._pids: Dict[str, int] = {}
        self._next_pid = 1
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._close_lock = threading.Lock()
        self._closed = False
        self._t0 = time.perf_counter()
        # Wall-clock anchor captured at the SAME instant as the
        # perf_counter epoch: offline merge aligns files via
        # wall_us = epoch_unix_us + ts, so n ranks' traces become
        # mergeable without the live KV offset handshake.  rank falls
        # back to the launcher-provided env identity (no jax import:
        # the timeline must open before backends initialize).
        self.epoch_unix_us = time.time() * 1e6
        if rank is None:
            for var in ("HVD_TPU_RANK", "HOROVOD_RANK"):
                v = os.environ.get(var, "")
                if v.lstrip("-").isdigit():
                    rank = int(v)
                    break
        self.rank = int(rank) if rank is not None else 0
        if hostname is None:
            import socket
            try:
                hostname = socket.gethostname()
            except OSError:
                hostname = "unknown"
        self.hostname = hostname
        self._events.append({
            "name": "clock_anchor", "ph": "M", "pid": 0,
            "args": {"epoch_unix_us": self.epoch_unix_us,
                     "rank": self.rank, "hostname": self.hostname}})
        self._file = open(path, "w")
        self._file.write("[\n")
        self._wrote_any = False
        self._flush_interval = flush_interval
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="hvd-tpu-timeline", daemon=True)
        self._writer.start()
        atexit.register(self.close)

    # -- event emission ---------------------------------------------------
    def _us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _pid(self, track: str) -> int:
        pid = self._pids.get(track)
        if pid is None:
            pid = self._next_pid
            self._next_pid += 1
            self._pids[track] = pid
            self._events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": track}})
        return pid

    def begin(self, tensor: str, phase: str,
              args: Optional[dict] = None) -> None:
        with self._lock:
            ev = {"name": phase, "ph": "B",
                  "pid": self._pid(tensor), "tid": 0,
                  "ts": self._us()}
            if args:
                ev["args"] = args
            self._events.append(ev)

    def end(self, tensor: str, phase: str,
            args: Optional[dict] = None) -> None:
        with self._lock:
            ev = {"name": phase, "ph": "E",
                  "pid": self._pid(tensor), "tid": 0,
                  "ts": self._us()}
            if args:
                ev["args"] = args
            self._events.append(ev)

    def complete(self, tensor: str, phase: str, dur_s: float,
                 args: Optional[dict] = None) -> None:
        """Retroactive Chrome "X" complete event spanning the PAST
        ``dur_s`` seconds and ending now -- for regions only measurable
        after the fact (the inter-dispatch gap: its start is known only
        once the next dispatch begins)."""
        with self._lock:
            ev = {"name": phase, "ph": "X",
                  "pid": self._pid(tensor), "tid": 0,
                  # Clamp to the trace epoch: a gap can predate open()
                  # (the first window of a freshly attached timeline).
                  "ts": max(0.0, self._us() - float(dur_s) * 1e6),
                  "dur": float(dur_s) * 1e6}
            if args:
                ev["args"] = args
            self._events.append(ev)

    def instant(self, name: str, track: str = "cycle") -> None:
        with self._lock:
            self._events.append({"name": name, "ph": "i", "s": "g",
                                 "pid": self._pid(track), "tid": 0,
                                 "ts": self._us()})

    def counter(self, name: str, value: float,
                track: str = "counters") -> None:
        """Chrome-trace counter sample ("C" event) -- renders as a
        stacked-area track (the reference plots tensor bytes this way)."""
        with self._lock:
            self._events.append({"name": name, "ph": "C",
                                 "pid": self._pid(track), "tid": 0,
                                 "ts": self._us(),
                                 "args": {name: float(value)}})

    def counters(self, values: Dict[str, float],
                 track: str = "counters") -> None:
        """Several counter samples at ONE timestamp (a single "C" event
        with multiple args renders as one stacked area).  Used by the
        fused deferred flush to emit its ``deferred_fused_buckets`` /
        fused-vs-singleton op counts as an atomic snapshot -- separate
        :meth:`counter` calls would get distinct timestamps and make the
        per-flush ratios unreadable in the trace viewer."""
        with self._lock:
            self._events.append({"name": "|".join(sorted(values)),
                                 "ph": "C",
                                 "pid": self._pid(track), "tid": 0,
                                 "ts": self._us(),
                                 "args": {k: float(v)
                                          for k, v in values.items()}})

    def mark_cycle(self) -> None:
        if self.mark_cycles:
            self.instant("CYCLE")

    @contextlib.contextmanager
    def range(self, tensor: str, phase: str, args: Optional[dict] = None):
        self.begin(tensor, phase, args=args)
        try:
            yield
        finally:
            self.end(tensor, phase)

    # -- writer thread ----------------------------------------------------
    def _drain(self) -> None:
        batch = []
        with self._lock:
            while self._events:
                batch.append(self._events.popleft())
        if not batch or self._file.closed:
            return
        chunks = []
        for ev in batch:
            prefix = ",\n" if self._wrote_any else ""
            self._wrote_any = True
            chunks.append(prefix + json.dumps(ev))
        self._file.write("".join(chunks))
        self._file.flush()

    def _writer_loop(self) -> None:
        while not self._stop.wait(self._flush_interval):
            try:
                self._drain()
            except ValueError:  # file closed under us at exit
                return

    def close(self) -> None:
        """Idempotent and exception-safe: ``hvd.shutdown()`` closes the
        timeline AND atexit fires the registration made in ``__init__``,
        so the double-close path is the normal path.  The writer thread
        is joined exactly once and the file closed exactly once, even if
        draining or the closing ``]`` write raises (e.g. a full disk) --
        a failed close must never wedge interpreter shutdown."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        self._writer.join(timeout=5)
        try:
            if not self._file.closed:
                self._drain()
                self._file.write("\n]\n")
        finally:
            try:
                self._file.close()
            except OSError:
                pass
            atexit.unregister(self.close)


class DispatchGapMonitor:
    """Per-window host-dispatch-gap fraction.

    The scan-loop layer exists to shrink host time that is NOT spent
    inside device dispatch/fetch calls -- Python glue, input handling,
    the per-step fence.  This monitor measures it directly: wrap every
    dispatch (step/loop call, final value fetch) in :meth:`dispatch`;
    per window, ``gap_fraction = 1 - dispatched_time / wall_time`` --
    the fraction of wall-clock the devices could have been starved by
    the host.  A k-step scan loop drives it toward zero because one
    dispatch covers k steps.

    Feeds ``bench.py``'s ``scanloop`` config and, when a
    :class:`Timeline` is active, a ``host_dispatch_gap`` counter track.
    """

    def __init__(self, timeline: Optional[Timeline] = None):
        self.timeline = timeline
        self.windows: list = []
        self._t0: Optional[float] = None
        self._dispatched = 0.0

    def begin_window(self) -> None:
        self._t0 = time.perf_counter()
        self._dispatched = 0.0

    @contextlib.contextmanager
    def dispatch(self):
        """Time one host->device dispatch (or device->host fetch)."""
        t = time.perf_counter()
        try:
            yield
        finally:
            self._dispatched += time.perf_counter() - t

    def end_window(self) -> float:
        """Close the window; returns (and records) its gap fraction."""
        if self._t0 is None:
            raise RuntimeError("end_window() without begin_window()")
        wall = time.perf_counter() - self._t0
        # Clamp dispatched time into [0, wall]: a clock stepping
        # backwards mid-window (mocked clocks, NTP slews) must yield a
        # fraction in [0, 1], never a negative gap or one above 1.
        dispatched = max(self._dispatched, 0.0)
        gap = 1.0 - min(dispatched / wall, 1.0) if wall > 0 else 0.0
        gap = min(max(gap, 0.0), 1.0)
        self.windows.append(gap)
        self._t0 = None
        if self.timeline is not None:
            self.timeline.counter("host_dispatch_gap", gap)
        from . import metrics as _metrics
        _metrics.registry().gauge(
            "horovod_dispatch_gap_fraction",
            "Last DispatchGapMonitor window: host time NOT spent "
            "dispatching (0 = devices never starved)").set(gap)
        return gap

    @property
    def gap_fraction(self) -> float:
        """Mean gap fraction over all closed windows (0.0 if none)."""
        if not self.windows:
            return 0.0
        return float(sum(self.windows) / len(self.windows))


class OverlapMonitor:
    """Per-window exchange-overlap fraction (the backward-overlap metric).

    The microbatched exchange (``training.py``, ``microbatches=k``) exists
    to hide gradient wire time behind backward compute.  This monitor
    reports how much of a known communication budget was actually hidden:
    give it the window's pure-compute time per step (``compute_s``, e.g.
    measured at n=1 or with the exchange disabled) and the predicted
    exchange time per step (``comm_s``, e.g. payload bytes / link
    bandwidth); per window of ``steps`` steps,

        exposed  = max(0, wall/steps - compute_s)   # comm NOT hidden
        hidden   = max(0, comm_s - exposed)
        fraction = hidden / comm_s                  # in [0, 1]

    1.0 means the exchange vanished behind compute (perfect overlap);
    0.0 means every wire second extended the step (no overlap -- the
    monolithic post-backward exchange).  ``comm_s <= 0`` (single chip, no
    exchange) records 0.0 by convention: there is nothing to hide.

    Feeds ``bench.py``'s ``overlap`` config and, when a
    :class:`Timeline` is active, an ``exchange_overlap`` counter track --
    the overlap analogue of :class:`DispatchGapMonitor`.
    """

    def __init__(self, compute_s: float, comm_s: float,
                 timeline: Optional[Timeline] = None):
        if compute_s < 0 or comm_s < 0:
            raise ValueError("compute_s and comm_s must be >= 0")
        self.compute_s = compute_s
        self.comm_s = comm_s
        self.timeline = timeline
        self.windows: list = []
        self._t0: Optional[float] = None

    def begin_window(self) -> None:
        self._t0 = time.perf_counter()

    def end_window(self, steps: int) -> float:
        """Close a window of ``steps`` steps; returns (and records) its
        overlap fraction."""
        if self._t0 is None:
            raise RuntimeError("end_window() without begin_window()")
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        wall = time.perf_counter() - self._t0
        self._t0 = None
        if self.comm_s <= 0.0:
            frac = 0.0
        else:
            exposed = max(0.0, wall / steps - self.compute_s)
            hidden = max(0.0, self.comm_s - exposed)
            frac = min(hidden / self.comm_s, 1.0)
        self.windows.append(frac)
        if self.timeline is not None:
            self.timeline.counter("exchange_overlap", frac)
        from . import metrics as _metrics
        _metrics.registry().gauge(
            "horovod_exchange_overlap_fraction",
            "Last OverlapMonitor window: fraction of the exchange "
            "hidden behind backward compute").set(frac)
        return frac

    @property
    def overlap_fraction(self) -> float:
        """Mean overlap fraction over all closed windows (0.0 if none)."""
        if not self.windows:
            return 0.0
        return float(sum(self.windows) / len(self.windows))


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a device-side profiler trace alongside the semantic
    timeline (SURVEY.md 5.1: ``jax.profiler`` owns device timing).

    Produces an XPlane/Perfetto trace under ``logdir`` viewable in
    TensorBoard or ui.perfetto.dev::

        with horovod_tpu.timeline.device_trace("/tmp/prof"):
            for _ in range(10):
                params, opt_state, loss = step(params, opt_state, batch)
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
