"""Clock alignment + cross-rank trace aggregation over the KV plane.

Per-rank Chrome traces are anchored to local clocks; merging them
requires knowing each rank's offset.  At ``init()`` (when
``HOROVOD_TRACE_SYNC=1`` and a rendezvous KV server is reachable) every
rank runs an NTP-style ping against the KV server's ``/time`` endpoint
(:func:`estimate_clock_offset`, transported by the existing
:class:`~horovod_tpu.run.http_kv.KVClient` and its
:class:`~horovod_tpu.run.retry.RetryPolicy`): for each sample,

    offset = server_time - (t_send + t_recv) / 2

keeping the minimum-round-trip sample (its midpoint uncertainty is
rtt/2, the NTP bound).  Rank r's offset *to rank 0* is then
``offset_r - offset_0`` -- both measured against the same server clock,
so the server's own absolute error cancels.

Every ``HOROVOD_TRACE_PUBLISH_STEPS`` steps each rank PUTs its compact
per-step span summary under ``trace/summary/<rank>/<step>``; rank 0
collects the fleet's summaries, feeds the
:class:`~horovod_tpu.timeline.straggler.StragglerMonitor`, and can
write one merged Perfetto trace (one pid per rank, offsets applied) via
:meth:`TracePlane.write_merged`.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("horovod_tpu.timeline")

SCOPE = "trace"

#: NTP-style ping samples per offset estimate.
OFFSET_SAMPLES = 8


def estimate_clock_offset(kv, samples: int = OFFSET_SAMPLES
                          ) -> Tuple[float, float]:
    """``(offset_s, rtt_s)`` of this host's clock relative to the KV
    server's, from ``samples`` round trips, keeping the minimum-RTT
    sample.  ``offset_s`` is what to ADD to a local wall-clock reading
    to land on the server's clock."""
    best: Optional[Tuple[float, float]] = None  # (rtt, offset)
    for _ in range(max(1, int(samples))):
        t0 = time.time()
        server_t = kv.server_time()
        t1 = time.time()
        rtt = max(0.0, t1 - t0)
        offset = server_t - (t0 + t1) / 2.0
        if best is None or rtt < best[0]:
            best = (rtt, offset)
    return best[1], best[0]


class TracePlane:
    """Per-rank publisher + (on rank 0) fleet collector."""

    def __init__(self, kv, rank: int, size: int,
                 publish_steps: int = 10, monitor=None):
        self.kv = kv
        self.rank = int(rank)
        self.size = max(1, int(size))
        self.publish_steps = max(1, int(publish_steps))
        self.monitor = monitor
        self.offset_s, self.rtt_s = estimate_clock_offset(kv)
        kv.put(SCOPE, f"offset/{self.rank}",
               json.dumps({"offset_s": self.offset_s,
                           "rtt_s": self.rtt_s}).encode())
        logger.info("trace plane: rank %d clock offset %+.3f ms to KV "
                    "server (rtt %.3f ms)", self.rank,
                    self.offset_s * 1e3, self.rtt_s * 1e3)
        self._offsets: Dict[int, float] = {self.rank: self.offset_s}
        self._collected: Dict[int, List[dict]] = {}

    # -- publish ----------------------------------------------------------
    def on_summary(self, summary: dict) -> None:
        """SpanRecorder listener: publish every N steps; never raises
        (a down driver must not take training with it)."""
        step = int(summary.get("step", 0))
        if step % self.publish_steps:
            return
        try:
            self.kv.put(SCOPE, f"summary/{summary['rank']}/{step}",
                        json.dumps(summary).encode())
            if self.rank == 0:
                self.collect(step)
        except Exception as e:
            logger.debug("trace plane publish failed at step %d: %s",
                         step, e)

    # -- collect (rank 0) -------------------------------------------------
    def rank_offset(self, rank: int) -> float:
        """Rank ``rank``'s clock offset relative to rank 0 (seconds)."""
        off = self._offsets.get(rank)
        if off is None:
            raw = self.kv.get(SCOPE, f"offset/{rank}")
            if raw is None:
                return 0.0
            off = float(json.loads(raw)["offset_s"])
            self._offsets[rank] = off
        return off - self._offsets.get(0, 0.0)

    def collect(self, step: int) -> List[dict]:
        """Fetch every rank's summary for ``step`` (missing ranks are
        skipped -- they may simply not have reached the publish point),
        feed the straggler monitor, and compute the step's skew."""
        out: List[dict] = []
        for r in range(self.size):
            raw = self.kv.get(SCOPE, f"summary/{r}/{step}")
            if raw is None:
                continue
            try:
                out.append(json.loads(raw))
            except ValueError:
                continue
        self._collected[step] = out
        if self.monitor is not None:
            for s in out:
                if int(s.get("rank", -1)) != self.rank:
                    # Our own summary already fed the monitor locally.
                    self.monitor.observe(s)
        return out

    # -- merged trace (rank 0) --------------------------------------------
    def write_merged(self, path: str) -> int:
        """Write collected summaries as ONE Perfetto/Chrome trace: one
        pid per rank, per-span-kind complete ("X") events placed on rank
        0's clock (offsets applied).  Returns the event count."""
        events: List[dict] = []
        for r in range(self.size):
            events.append({"name": "process_name", "ph": "M",
                           "pid": r + 1,
                           "args": {"name": f"rank {r}"}})
        n = 0
        for step in sorted(self._collected):
            for s in self._collected[step]:
                r = int(s["rank"])
                t0 = float(s["t0_us"]) - self.rank_offset(r) * 1e6
                events.append({
                    "name": f"step {step}", "ph": "X", "pid": r + 1,
                    "tid": 0, "ts": t0,
                    "dur": float(s["wall_s"]) * 1e6,
                    "args": {"rank": r, "step": step}})
                cursor = t0
                for kind, secs in sorted((s.get("spans") or {}).items()):
                    events.append({
                        "name": kind, "ph": "X", "pid": r + 1, "tid": 1,
                        "ts": cursor, "dur": float(secs) * 1e6,
                        "args": {"rank": r, "step": step, "kind": kind}})
                    cursor += float(secs) * 1e6
                n += 1
        with open(path, "w") as f:
            json.dump(events, f)
        return n

    def step_skew(self, step: int) -> Optional[float]:
        """Slowest-minus-fastest wall among collected summaries for
        ``step`` (None with fewer than two ranks reporting)."""
        walls = [float(s["wall_s"]) for s in self._collected.get(step, [])]
        if len(walls) < 2:
            return None
        return max(walls) - min(walls)
