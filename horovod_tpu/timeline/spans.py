"""Cross-rank span layer: tagged timing spans + per-step summaries.

Every host-side timing region in the exchange path funnels through the
process-wide :class:`SpanRecorder`: eager collective dispatch and fence
waits (``collectives/eager.py``), fused deferred-flush buckets, and the
jitted step's dispatch / dispatch-gap (``training._InstrumentedStep``).
Each span is tagged ``(rank, step, bucket_id, fuse_key, leg)`` and, when
a :class:`~horovod_tpu.timeline.Timeline` is attached, mirrored into the
Chrome-trace file so one rank's file already carries the attribution the
cross-rank merge needs.

In-jit exchange legs (``collectives/ops.py``, ``optim/zero.py``,
``optim/distributed.py``) cannot be host-timed span-by-span -- XLA owns
their schedule.  They register themselves at *trace time* via
:func:`note_leg` instead (the same host-side-effect idiom as
``optim/distributed._note_compression_ratio``: fires once per trace, so
retraces refresh it and cached executions cost nothing).  The registered
byte counts let the straggler report attribute a compiled step's
exchange time across legs proportionally.

Per step, the recorder folds its spans into a compact summary dict::

    {"rank": r, "step": s, "t0_us": <unix epoch us at dispatch start>,
     "wall_s": ..., "spans": {"dispatch": ..., "dispatch_gap": ...,
     "exchange": ..., "fence": ..., "bucket": ...}, "legs": {...}}

which feeds the :class:`~horovod_tpu.timeline.straggler.StragglerMonitor`
locally and, under ``HOROVOD_TRACE_SYNC=1``, the KV trace plane
(``timeline/sync.py``) for rank 0 to merge.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

#: Span kinds a step decomposes into.  "dispatch" is the jitted-step
#: dispatch call; "dispatch_gap" the host time between consecutive
#: dispatches (input pipeline, Python glue, injected host delays);
#: "exchange" an eager collective execution; "fence" a blocking
#: device->host wait; "bucket" one fused deferred-flush unit;
#: "negotiate" trace+compile on an executable-cache miss.
SPAN_KINDS = ("dispatch", "dispatch_gap", "exchange", "fence", "bucket",
              "negotiate", "compute")

#: Per-step summaries kept in the ring buffer.
SUMMARY_RING = 64


class SpanRecorder:
    """Process-wide span sink; cheap enough to call per collective."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rank = 0
        self.timeline = None  # Optional[Timeline]
        self._step = 0
        # step -> {"spans": {kind: secs}, "tags": [...]}  (ring)
        self._acc: "OrderedDict[int, dict]" = OrderedDict()
        self.summaries: "OrderedDict[int, dict]" = OrderedDict()
        # trace-time leg registry: leg -> {"nbytes": n, "buckets": k}
        self.legs: Dict[str, dict] = {}
        self._listeners = []

    # -- wiring -----------------------------------------------------------
    def configure(self, rank: Optional[int] = None,
                  timeline=None) -> "SpanRecorder":
        with self._lock:
            if rank is not None:
                self.rank = int(rank)
            if timeline is not None:
                self.timeline = timeline
        return self

    def add_listener(self, fn) -> None:
        """``fn(summary_dict)`` called after every step boundary.
        Idempotent by identity (re-init must not double-feed)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- step clock -------------------------------------------------------
    def set_step(self, step: int) -> None:
        self._step = int(step)

    @property
    def step(self) -> int:
        return self._step

    def _bucket(self, step: int) -> dict:
        acc = self._acc.get(step)
        if acc is None:
            acc = self._acc[step] = {"spans": {}, "legs": {}}
            while len(self._acc) > SUMMARY_RING:
                self._acc.popitem(last=False)
        return acc

    # -- span emission ----------------------------------------------------
    def add(self, kind: str, dur_s: float, leg: Optional[str] = None,
            bucket_id: Optional[int] = None,
            fuse_key: Optional[str] = None, emit: bool = False) -> None:
        """Record a completed span of ``dur_s`` seconds at the current
        step (the non-contextmanager form, for callers that already
        timed the region themselves).  ``emit=True`` mirrors it into the
        attached timeline as a retroactive "X" event ending now -- used
        for regions with no begin/end pair of their own (the dispatch
        gap); callers whose region already has a timeline range must
        leave it False or the merge would double-count."""
        with self._lock:
            acc = self._bucket(self._step)
            acc["spans"][kind] = acc["spans"].get(kind, 0.0) + float(dur_s)
            if leg:
                lg = acc["legs"].setdefault(leg, {"secs": 0.0, "count": 0})
                lg["secs"] += float(dur_s)
                lg["count"] += 1
        if emit:
            tl = self.timeline
            if tl is not None:
                args = {"rank": self.rank, "step": self._step}
                if leg is not None:
                    args["leg"] = leg
                if bucket_id is not None:
                    args["bucket_id"] = int(bucket_id)
                if fuse_key is not None:
                    args["fuse_key"] = str(fuse_key)
                try:
                    tl.complete("spans", kind, dur_s, args=args)
                except Exception:
                    pass

    @contextlib.contextmanager
    def span(self, kind: str, name: str = "", leg: Optional[str] = None,
             bucket_id: Optional[int] = None,
             fuse_key: Optional[str] = None):
        """Time a host region and tag it ``(rank, step, bucket_id,
        fuse_key, leg)``.  Mirrors into the Chrome-trace timeline (one
        ``spans`` track, args carry the tags) when one is attached."""
        tl = self.timeline
        args = None
        if tl is not None:
            args = {"rank": self.rank, "step": self._step}
            if leg is not None:
                args["leg"] = leg
            if bucket_id is not None:
                args["bucket_id"] = int(bucket_id)
            if fuse_key is not None:
                args["fuse_key"] = str(fuse_key)
            tl.begin(name or "spans", kind, args=args)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            if tl is not None:
                tl.end(name or "spans", kind)
            self.add(kind, dur, leg=leg, bucket_id=bucket_id,
                     fuse_key=fuse_key)

    # -- trace-time leg registry ------------------------------------------
    def note_leg(self, leg, nbytes: Optional[int] = None,
                 bucket_id: Optional[int] = None,
                 fuse_key: Optional[str] = None) -> None:
        """Register an in-jit exchange leg (called at TRACE time from
        inside jitted code -- a host side effect that fires once per
        trace, like ``_note_compression_ratio``).  The byte totals let
        the offline report split compiled-step exchange time across
        legs; they are per-trace wire payloads, not per-step timings.

        ``leg`` is either a plan-IR ``ExchangeLeg`` row (preferred: the
        tag AND byte count come from the plan, so the registry renders
        the IR verbatim) or a bare tag string.  All entry points -- this
        method and the module-level :func:`note_leg` -- normalize
        through :func:`_normalize_leg`, the single tag/byte derivation
        path."""
        leg, nbytes = _normalize_leg(leg, nbytes)
        with self._lock:
            lg = self.legs.setdefault(leg, {"nbytes": 0, "buckets": 0})
            lg["nbytes"] += int(nbytes)
            lg["buckets"] += 1
        tl = self.timeline
        if tl is not None:
            try:
                tl.counter(f"leg_bytes/{leg}", float(nbytes))
            except Exception:
                pass

    # -- step boundary ----------------------------------------------------
    def step_boundary(self, step: int, wall_s: float,
                      t0_unix_us: Optional[float] = None) -> dict:
        """Close step ``step``: fold accumulated spans into a summary,
        push it through the listeners (straggler monitor, KV publisher)
        and return it.  ``wall_s`` is the full step wall including the
        dispatch gap; ``t0_unix_us`` anchors the step on the wall clock
        for the cross-rank merge."""
        with self._lock:
            acc = self._acc.pop(step, {"spans": {}, "legs": {}})
            summary = {
                "rank": self.rank,
                "step": int(step),
                "t0_us": float(t0_unix_us if t0_unix_us is not None
                               else time.time() * 1e6),
                "wall_s": float(wall_s),
                "spans": {k: round(v, 9)
                          for k, v in sorted(acc["spans"].items())},
                "legs": {k: {"secs": round(v["secs"], 9),
                             "count": v["count"]}
                         for k, v in sorted(acc["legs"].items())},
            }
            self.summaries[step] = summary
            while len(self.summaries) > SUMMARY_RING:
                self.summaries.popitem(last=False)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(summary)
            except Exception:  # observers must never break training
                pass
        return summary

    def reset(self) -> None:
        """Forget accumulated state (tests / re-init)."""
        with self._lock:
            self._step = 0
            self._acc.clear()
            self.summaries.clear()
            self.legs.clear()
            self._listeners = []
            self.timeline = None
            self.rank = 0


def dominant_span(summary: dict) -> str:
    """The span kind that ate the most host time in a step summary
    (``"compute"`` when the dispatch dominates and nothing else is
    recorded -- on the scan-loop path the device work hides behind one
    dispatch)."""
    spans = summary.get("spans") or {}
    if not spans:
        return "compute"
    return max(spans.items(), key=lambda kv: kv[1])[0]


_recorder = SpanRecorder()


def recorder() -> SpanRecorder:
    """The process-wide :class:`SpanRecorder` singleton."""
    return _recorder


def _normalize_leg(leg, nbytes: Optional[int] = None):
    """THE tag-normalization path for leg registration.

    Accepts a plan-IR leg row (anything with ``.tag``/``.nbytes`` --
    ``controller.fusion.ExchangeLeg``) or a bare tag string.  When the
    caller passes an IR row and no byte override, the leg's planned wire
    bytes are recorded -- the registry then renders the IR verbatim and
    executor-emitted tags cannot drift from plan-rendered tags.  Both
    ``SpanRecorder.note_leg`` and the module-level :func:`note_leg`
    funnel through here (there is no second derivation)."""
    tag = getattr(leg, "tag", None)
    if tag is not None:
        if nbytes is None:
            nbytes = getattr(leg, "nbytes", 0)
        return str(tag), int(nbytes)
    return str(leg), int(nbytes if nbytes is not None else 0)


def note_leg(leg, nbytes: Optional[int] = None,
             bucket_id: Optional[int] = None,
             fuse_key: Optional[str] = None) -> None:
    """Module-level convenience for in-jit call sites (keeps the traced
    code's import surface to one function).  Delegates to the recorder
    method; tag normalization happens exactly once, in
    :func:`_normalize_leg`."""
    _recorder.note_leg(leg, nbytes=nbytes, bucket_id=bucket_id,
                       fuse_key=fuse_key)
