"""Process-wide metrics plane (the aggregate half of SURVEY.md 5.1).

The Chrome-trace :class:`~horovod_tpu.timeline.Timeline` captures the
*semantic lifecycle* of each operation; this module answers "how is the
job doing right now": a process-wide :class:`MetricsRegistry` of
counters, gauges and fixed-bucket histograms that every telemetry source
in the runtime feeds --

- the per-step :class:`StepReport` sampled host-side around each
  executable call (``training.py``; wall time, exchanged wire bytes,
  codec, microbatches, steps-per-exec),
- :class:`~horovod_tpu.timeline.DispatchGapMonitor` /
  :class:`~horovod_tpu.timeline.OverlapMonitor` window fractions,
- ``controller.fusion.plan_cache_stats()`` and
  ``collectives.eager.deferred_fuse_stats()`` (pulled lazily through
  registered collectors so resets stay consistent),
- compression ratio / wire-bytes accounting from ``optim/distributed.py``,
- eager-path op and fence counts from ``collectives/eager.py``,
- elastic rank-change events and autotuner sample decisions.

Rendered two ways: Prometheus text exposition (served by
``run/metrics_server.py`` on ``HOROVOD_METRICS_PORT``) and a plain dict
via :func:`metrics_snapshot` (recorded into ``BENCH_*.json`` by
``bench.py``).

Zero-overhead when disabled (``HOROVOD_METRICS=0``): every family
accessor returns a shared null object whose ``inc``/``set``/``observe``
are no-ops, and the train-step instrumentation unwraps entirely.
Nothing here runs inside a traced program -- scan-loop bitwise parity
and buffer donation are untouched by construction.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "MetricsRegistry", "StepReport", "registry", "reset_metrics",
    "metrics_snapshot", "render_prometheus", "last_step_report",
    "record_step_report", "install_default_metrics", "bench_block",
]

# Step wall-time histogram upper bounds (seconds).  Spans sub-ms eager
# dispatches to multi-second big-model scan executables.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render without the dot."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


# -- metric primitives ----------------------------------------------------

class Counter:
    """Monotonic counter.  ``set_cumulative`` exists for collector-fed
    counters whose source keeps its own running total (plan cache,
    deferred-fuse stats): the collector publishes the absolute value
    instead of diffing."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(f"counter increment must be >= 0, got {v}")
        with self._lock:
            self._value += v

    def set_cumulative(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Instantaneous value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with cumulative Prometheus semantics.

    ``buckets`` are strictly-increasing upper bounds; an implicit
    ``+Inf`` bucket always exists.  ``snapshot()`` returns CUMULATIVE
    per-``le`` counts (each bucket includes everything below it), the
    way the text format and every bucket-arithmetic test expect."""

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError(
                f"bucket bounds must be strictly increasing: {bounds}")
        self._lock = threading.Lock()
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)  # le semantics: v <= bound
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            raw = list(self._counts)
            total, s = self._count, self._sum
        cum, acc = {}, 0
        for bound, c in zip(self.bounds, raw):
            acc += c
            cum[_fmt(bound)] = acc
        cum["+Inf"] = total
        return {"buckets": cum, "sum": s, "count": total}


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _NullMetric:
    """Shared no-op stand-in returned when metrics are disabled: absorbs
    the whole family/child API so call sites never branch."""

    __slots__ = ()

    def inc(self, v: float = 1.0) -> None:
        pass

    def dec(self, v: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def set_cumulative(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def labels(self, **kv) -> "_NullMetric":
        return self

    @property
    def value(self) -> float:
        return 0.0


NULL_METRIC = _NullMetric()


class _Family:
    """One named metric family, optionally labelled.  An unlabelled
    family proxies the metric API straight to its single child, so
    ``reg.counter("x").inc()`` and ``reg.gauge("y").set(v)`` both read
    naturally."""

    __slots__ = ("kind", "name", "help", "labelnames", "buckets",
                 "_lock", "_children")

    def __init__(self, kind: str, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        self.kind = kind
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _make(self):
        if self.kind == "histogram":
            return Histogram(self.buckets)
        return _KINDS[self.kind]()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != "
                f"declared {sorted(self.labelnames)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
            return child

    def _solo(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labelled {self.labelnames}; use .labels()")
        return self.labels()

    # unlabelled convenience pass-throughs
    def inc(self, v: float = 1.0) -> None:
        self._solo().inc(v)

    def dec(self, v: float = 1.0) -> None:
        self._solo().dec(v)

    def set(self, v: float) -> None:
        self._solo().set(v)

    def set_cumulative(self, v: float) -> None:
        self._solo().set_cumulative(v)

    def observe(self, v: float) -> None:
        self._solo().observe(v)

    def snapshot(self) -> dict:
        return self._solo().snapshot()

    @property
    def value(self) -> float:
        return self._solo().value

    def samples(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


# -- the registry ---------------------------------------------------------

class MetricsRegistry:
    """Thread-safe family store + collector callbacks + renderers.

    Enabled-ness is evaluated lazily at family-access time so the
    registry is robust to creation order: before ``hvd.init()`` it
    follows ``HOROVOD_METRICS`` directly, afterwards the frozen
    :class:`~horovod_tpu.core.config.Config` wins."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[[], None]] = []
        self._last_report: Optional["StepReport"] = None

    @property
    def enabled(self) -> bool:
        from ..core.config import _env_bool
        from ..core.state import global_state
        cfg = global_state().config
        if cfg is not None and hasattr(cfg, "metrics_enabled"):
            return bool(cfg.metrics_enabled)
        return _env_bool("METRICS", True)

    # -- family accessors -------------------------------------------------
    def _family(self, kind: str, name: str, help: str,
                labelnames: Sequence[str],
                buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        if not self.enabled:
            return NULL_METRIC
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(kind, name, help, labelnames, buckets)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()):
        return self._family("counter", name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()):
        return self._family("gauge", name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
                  labelnames: Sequence[str] = ()):
        return self._family("histogram", name, help, labelnames, buckets)

    # -- collectors -------------------------------------------------------
    def add_collector(self, fn: Callable[[], None]) -> None:
        """Register a pull callback run before every render/snapshot.
        Idempotent by identity; use for sources that keep their own
        running totals (plan cache, deferred-fuse stats)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # a broken collector must not kill a scrape
                pass

    # -- step reports ------------------------------------------------------
    def record_step_report(self, report: "StepReport") -> None:
        with self._lock:
            self._last_report = report

    @property
    def last_step_report(self) -> Optional["StepReport"]:
        with self._lock:
            return self._last_report

    # -- rendering ---------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self.collect()
        with self._lock:
            families = [self._families[n] for n in sorted(self._families)]
        out: List[str] = []
        for fam in families:
            out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for key, metric in fam.samples():
                base = "".join(
                    f'{n}="{_escape_label_value(v)}",'
                    for n, v in zip(fam.labelnames, key))[:-1]
                if fam.kind == "histogram":
                    snap = metric.snapshot()
                    for le, c in snap["buckets"].items():
                        lbl = (base + "," if base else "") + \
                            f'le="{_escape_label_value(le)}"'
                        out.append(f"{fam.name}_bucket{{{lbl}}} {c}")
                    suffix = f"{{{base}}}" if base else ""
                    out.append(f"{fam.name}_sum{suffix} "
                               f"{_fmt(snap['sum'])}")
                    out.append(f"{fam.name}_count{suffix} {snap['count']}")
                else:
                    suffix = f"{{{base}}}" if base else ""
                    out.append(f"{fam.name}{suffix} {_fmt(metric.value)}")
        return "\n".join(out) + "\n" if out else ""

    def snapshot(self) -> dict:
        """Plain-dict snapshot: unlabelled counter/gauge -> ``value``;
        histogram -> ``count``/``sum``/``buckets``; labelled families ->
        a ``samples`` list."""
        self.collect()
        with self._lock:
            families = dict(self._families)
        snap: Dict[str, dict] = {}
        for name in sorted(families):
            fam = families[name]
            entry: dict = {"type": fam.kind}
            if fam.labelnames:
                entry["samples"] = [
                    {"labels": dict(zip(fam.labelnames, key)),
                     **(m.snapshot() if fam.kind == "histogram"
                        else {"value": m.value})}
                    for key, m in fam.samples()]
            else:
                kids = fam.samples()
                if not kids:
                    entry["value"] = 0.0
                elif fam.kind == "histogram":
                    entry.update(kids[0][1].snapshot())
                else:
                    entry["value"] = kids[0][1].value
            snap[name] = entry
        return snap


# -- process-wide singleton ------------------------------------------------

_registry_lock = threading.Lock()
_registry: Optional[MetricsRegistry] = None


def registry() -> MetricsRegistry:
    """The process-wide registry (created on first use)."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry


def reset_metrics() -> None:
    """Drop every family, collector and step report (tests)."""
    global _registry
    with _registry_lock:
        _registry = None


def metrics_snapshot() -> dict:
    """Public snapshot API: ``horovod_tpu.metrics_snapshot()``."""
    return registry().snapshot()


def render_prometheus() -> str:
    return registry().render()


# -- per-step report -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class StepReport:
    """Host-side sample of ONE executable dispatch in the train loop.

    ``wall_time_s`` is the dispatch wall time for the whole call (a
    ``steps_per_exec=k`` scan loop covers k optimizer steps).
    ``exchanged_bytes``/``uncompressed_bytes`` are the per-optimizer-step
    wire accounting: for ZeRO-1 they match
    ``zero_report()['zero1_exchanged_bytes_per_chip']`` /
    ``['replicated_allreduce_bytes_per_chip']`` byte-for-byte; for a
    compressed exchange they match ``bench.py``'s
    ``wire_payload_bytes``-over-``ef_bucket_plan`` accounting.  The
    microbatch overlap factor is intentionally NOT folded in: the figure
    is the equivalent single-exchange payload."""

    step: int
    wall_time_s: float
    steps_per_exec: int = 1
    microbatches: int = 1
    zero_stage: int = 0
    codec: str = "none"
    exchanged_bytes: int = 0
    uncompressed_bytes: int = 0


def last_step_report() -> Optional[StepReport]:
    """The most recent :class:`StepReport` (None before the first step)."""
    return registry().last_step_report


def record_step_report(report: StepReport) -> None:
    """Store ``report`` and feed the step-level families."""
    reg = registry()
    if not reg.enabled:
        return
    reg.record_step_report(report)
    k = max(int(report.steps_per_exec), 1)
    reg.counter("horovod_step_total",
                "Optimizer steps completed").inc(k)
    reg.histogram("horovod_step_time_seconds",
                  "Per-step dispatch wall time (scan loops amortize "
                  "one dispatch over k steps)").observe(
                      report.wall_time_s / k)
    reg.counter("horovod_wire_bytes_total",
                "Cumulative per-chip gradient-exchange wire bytes"
                ).inc(report.exchanged_bytes * k)
    reg.gauge("horovod_wire_bytes_per_step",
              "Per-chip exchange wire bytes per optimizer step"
              ).set(report.exchanged_bytes)
    reg.gauge("horovod_uncompressed_bytes_per_step",
              "Equivalent uncompressed exchange bytes per optimizer step"
              ).set(report.uncompressed_bytes)
    if report.exchanged_bytes > 0 and report.uncompressed_bytes > 0:
        reg.gauge("horovod_compression_ratio",
                  "uncompressed / wire bytes of the gradient exchange"
                  ).set(report.uncompressed_bytes / report.exchanged_bytes)


# -- default families + collectors -----------------------------------------

def _collect_plan_cache() -> None:
    from ..controller.fusion import plan_cache_stats
    reg = registry()
    stats = plan_cache_stats()
    reg.counter("horovod_plan_cache_hits_total",
                "Fusion bucket-plan cache hits"
                ).set_cumulative(stats["hits"])
    reg.counter("horovod_plan_cache_misses_total",
                "Fusion bucket-plan cache misses"
                ).set_cumulative(stats["misses"])
    reg.counter("horovod_plan_cache_evictions_total",
                "Fusion bucket-plan cache evictions"
                ).set_cumulative(stats["evictions"])
    reg.gauge("horovod_plan_cache_size",
              "Fusion bucket-plan cache entries").set(stats["size"])


def _collect_deferred_fuse() -> None:
    from ..collectives.eager import deferred_fuse_stats
    reg = registry()
    stats = deferred_fuse_stats()
    reg.counter("horovod_deferred_flushes_total",
                "Deferred-async flush rounds"
                ).set_cumulative(stats["flushes"])
    reg.counter("horovod_deferred_fused_buckets_total",
                "Fusion-planner buckets dispatched by the deferred flush"
                ).set_cumulative(stats["fused_buckets"])
    reg.counter("horovod_deferred_fused_ops_total",
                "Deferred ops serviced through a fused bucket"
                ).set_cumulative(stats["fused_ops"])
    reg.counter("horovod_deferred_singleton_ops_total",
                "Deferred ops dispatched individually"
                ).set_cumulative(stats["singleton_ops"])


def _collect_eager() -> None:
    from ..collectives.eager import eager_op_stats
    reg = registry()
    stats = eager_op_stats()
    reg.counter("horovod_eager_ops_total",
                "Eager collective dispatches"
                ).set_cumulative(stats["ops"])
    reg.counter("horovod_eager_fences_total",
                "Eager coordination fences (named-barrier rounds)"
                ).set_cumulative(stats["fences"])


def install_default_metrics() -> None:
    """Eagerly create the default families and wire the pull collectors.

    Idempotent; called from ``hvd.init()`` and from the metrics server
    so a scrape during a plain train loop always exposes the full
    family set (>= 8 families) even before every source has fired."""
    reg = registry()
    if not reg.enabled:
        return
    reg.counter("horovod_step_total", "Optimizer steps completed")
    reg.histogram("horovod_step_time_seconds",
                  "Per-step dispatch wall time (scan loops amortize "
                  "one dispatch over k steps)")
    reg.counter("horovod_wire_bytes_total",
                "Cumulative per-chip gradient-exchange wire bytes")
    reg.gauge("horovod_wire_bytes_per_step",
              "Per-chip exchange wire bytes per optimizer step")
    reg.gauge("horovod_uncompressed_bytes_per_step",
              "Equivalent uncompressed exchange bytes per optimizer step")
    reg.gauge("horovod_compression_ratio",
              "uncompressed / wire bytes of the gradient exchange")
    reg.gauge("horovod_dispatch_gap_fraction",
              "Last DispatchGapMonitor window: host time NOT spent "
              "dispatching (0 = devices never starved)")
    reg.gauge("horovod_exchange_overlap_fraction",
              "Last OverlapMonitor window: fraction of the exchange "
              "hidden behind backward compute")
    reg.gauge("horovod_plan_buckets",
              "Bucket count of the most recently explained exchange plan")
    reg.counter("horovod_elastic_reset_total",
                "Elastic state resets (rank-change recoveries)")
    reg.counter("horovod_elastic_host_updates_total",
                "Elastic host-set update notifications")
    reg.counter("horovod_elastic_ranks_lost",
                "Ranks lost across elastic recoveries")
    reg.gauge("horovod_elastic_steps_to_recover",
              "Steps rolled back to the last commit during the most "
              "recent elastic recovery")
    reg.counter("horovod_ef_residual_recovered_bytes",
                "Bytes of optimizer/EF carry state reconstructed "
                "checkpointlessly across elastic resizes")
    reg.counter("horovod_ef_residual_zeroed_total",
                "EF residual buckets dropped (zeroed) during an elastic "
                "resize because shapes were irreconcilable")
    reg.counter("horovod_chaos_faults_total",
                "Faults fired by the chaos injector")
    reg.counter("horovod_kv_retries_total",
                "Control-plane requests retried after a transport "
                "failure")
    reg.counter("horovod_autotune_samples_total",
                "Autotuner samples scored (one per sample window)")
    # Serving control-plane decision families (serving.controlplane).
    reg.counter("horovod_ctl_decisions_total",
                "Serving control-plane decisions by action",
                labelnames=("action",))
    reg.counter("horovod_ctl_resizes_total",
                "Decode-mesh resizes executed by the control plane",
                labelnames=("direction",))
    reg.counter("horovod_ctl_evictions_total",
                "Ranks removed from the serving fleet by the control "
                "plane", labelnames=("reason",))
    reg.counter("horovod_ctl_drained_requests_total",
                "In-flight requests carried through a resize, by drain "
                "path", labelnames=("path",))
    reg.counter("horovod_ctl_slo_violation_seconds_total",
                "Seconds the sampled SLO (TTFT p99 / queue depth) was "
                "in violation")
    reg.gauge("horovod_ctl_mesh_size",
              "Current decode-mesh tensor-parallel size")
    reg.gauge("horovod_ctl_healthy_ranks",
              "Devices the control plane still considers usable")
    reg.gauge("horovod_ctl_ttft_p99_seconds",
              "Windowed TTFT p99 as sampled by the control plane")
    reg.add_collector(_collect_plan_cache)
    reg.add_collector(_collect_deferred_fuse)
    reg.add_collector(_collect_eager)


# -- histogram arithmetic --------------------------------------------------

def histogram_window(curr: dict, base: Optional[dict]) -> dict:
    """Subtract a baseline cumulative snapshot from a newer one.

    Both arguments are ``Histogram.snapshot()`` dicts.  The result
    covers only the observations made after ``base`` was taken -- how
    the serving control plane turns the process-lifetime TTFT histogram
    into a per-sampling-window distribution (the registry is
    append-only, so windows are diffs, as with PromQL ``increase()``).
    """
    if not base:
        return curr
    base_buckets = base.get("buckets", {})
    return {
        "buckets": {le: int(c) - int(base_buckets.get(le, 0))
                    for le, c in curr["buckets"].items()},
        "sum": float(curr.get("sum", 0.0)) - float(base.get("sum", 0.0)),
        "count": int(curr.get("count", 0)) - int(base.get("count", 0)),
    }


def histogram_quantile(snap: dict, q: float) -> Optional[float]:
    """Quantile estimate from a cumulative ``Histogram.snapshot()``.

    Prometheus ``histogram_quantile`` semantics: find the first bucket
    whose cumulative count covers rank ``q * count`` and interpolate
    linearly inside it; observations in the ``+Inf`` overflow clamp to
    the highest finite bound.  Returns ``None`` on an empty snapshot.
    """
    total = int(snap.get("count", 0))
    if total <= 0:
        return None
    items = sorted(
        (float("inf") if le == "+Inf" else float(le), int(c))
        for le, c in snap.get("buckets", {}).items())
    rank = max(0.0, min(1.0, float(q))) * total
    prev_bound, prev_count = 0.0, 0
    for bound, count in items:
        if count >= rank and count > prev_count:
            if bound == float("inf"):
                return prev_bound
            frac = (rank - prev_count) / (count - prev_count)
            return prev_bound + (bound - prev_bound) * frac
        prev_count = count
        if bound != float("inf"):
            prev_bound = bound
    return None


# -- bench integration -----------------------------------------------------

def bench_block(snap: Optional[dict] = None) -> dict:
    """Compact snapshot block recorded into each ``BENCH_*.json``.

    Shape is validated by ``tests/test_bench_guard.py``'s
    ``scan_metrics_snapshot_entries``: counters non-negative, and when a
    ``compression`` entry is present with matching wire bytes, the
    gauge-implied ratio must agree with it."""
    if snap is None:
        snap = metrics_snapshot()

    def val(name: str, default: float = 0.0) -> float:
        fam = snap.get(name) or {}
        return float(fam.get("value", default))

    hist = snap.get("horovod_step_time_seconds") or {}
    ratio = val("horovod_compression_ratio")
    return {
        "families": len(snap),
        "step_total": int(val("horovod_step_total")),
        "step_time_count": int(hist.get("count", 0)),
        "step_time_sum_s": round(float(hist.get("sum", 0.0)), 6),
        "wire_bytes_total": int(val("horovod_wire_bytes_total")),
        "wire_bytes_per_step": int(val("horovod_wire_bytes_per_step")),
        "uncompressed_bytes_per_step": int(
            val("horovod_uncompressed_bytes_per_step")),
        "compression_ratio": round(ratio, 4) if ratio > 0 else None,
        "plan_cache_hits": int(val("horovod_plan_cache_hits_total")),
        "plan_cache_misses": int(val("horovod_plan_cache_misses_total")),
    }
