"""Straggler attribution over per-step span summaries.

The reference answers "which rank is late" with the timeline plus the
stall-check warning; at pod scale the question needs per-leg attribution
too.  :class:`StragglerMonitor` consumes the compact per-step summaries
the :class:`~horovod_tpu.timeline.spans.SpanRecorder` emits -- locally
on every rank, and (under ``HOROVOD_TRACE_SYNC=1``) cross-rank on rank 0
via the KV trace plane -- and keeps:

* a per-rank step-wall EWMA; lateness = EWMA minus the fleet-fastest
  EWMA, the straggler is the rank with the largest lateness;
* per-step skew (slowest minus fastest wall among ranks that reported
  the step), fed into a histogram;
* the straggler's *dominant span kind* (dispatch gap vs exchange vs
  fence vs compute), naming WHERE the late rank spends its step.

Exports through the PR-6 metrics registry::

    horovod_straggler_rank                  gauge
    horovod_straggler_lateness_seconds      gauge
    horovod_straggler_rank_wall_seconds     gauge{rank=...}
    horovod_step_skew_seconds               histogram
    horovod_step_skew_last_seconds          gauge

and logs a stall warning when a rank that has reported before goes
silent for longer than ``HOROVOD_STALL_CHECK_TIME_SECONDS`` (the same
knob the core stall inspector honours).

The serving control plane attaches an *eviction hook*
(:meth:`StragglerMonitor.add_eviction_hook`): when the straggler's
lateness EWMA crosses the hook's threshold the callback fires once per
rank (latched), outside the monitor lock, and the controller answers by
draining that rank out of the decode mesh and calling
:meth:`StragglerMonitor.evict` so attribution continues over the
survivors instead of pinning the dead EWMA as straggler forever.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

from .spans import dominant_span

logger = logging.getLogger("horovod_tpu.timeline")

#: Skew histogram bounds (seconds): sub-ms jitter up to multi-second
#: stalls.
SKEW_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                5.0, 30.0)

#: Per-step observation window kept for skew computation.
_STEP_RING = 128


class StragglerMonitor:
    """Per-rank lateness EWMAs + per-step skew over span summaries."""

    def __init__(self, world: int = 1, alpha: float = 0.3,
                 stall_check_time: float = 60.0):
        self.world = max(1, int(world))
        self.alpha = float(alpha)
        self.stall_check_time = float(stall_check_time)
        self._lock = threading.Lock()
        self._ewma: Dict[int, float] = {}          # rank -> wall EWMA (s)
        self._last_summary: Dict[int, dict] = {}   # rank -> newest summary
        self._last_seen: Dict[int, float] = {}     # rank -> monotonic ts
        self._steps: "OrderedDict[int, Dict[int, float]]" = OrderedDict()
        self._warned_stalled: set = set()
        self.observations = 0
        self._evict_hooks: list = []   # (threshold_s, callback)
        self._evict_fired: set = set()  # ranks a hook already fired for
        self._evict_streak: tuple = (None, 0)  # (rank, consecutive evals)

    # -- ingestion --------------------------------------------------------
    def observe(self, summary: dict, now: Optional[float] = None) -> None:
        """Feed one per-step summary (any rank's).  Never raises."""
        try:
            rank = int(summary["rank"])
            step = int(summary["step"])
            wall = float(summary["wall_s"])
        except (KeyError, TypeError, ValueError):
            return
        mono = time.monotonic() if now is None else float(now)
        with self._lock:
            self.observations += 1
            prev = self._ewma.get(rank)
            self._ewma[rank] = wall if prev is None else \
                self.alpha * wall + (1.0 - self.alpha) * prev
            self._last_summary[rank] = summary
            self._last_seen[rank] = mono
            if rank in self._warned_stalled:
                self._warned_stalled.discard(rank)
            walls = self._steps.setdefault(step, {})
            walls[rank] = wall
            while len(self._steps) > _STEP_RING:
                self._steps.popitem(last=False)
            skew = (max(walls.values()) - min(walls.values())
                    if len(walls) >= 2 else None)
        self._export(skew)
        self._check_stalled(mono)
        self._check_eviction()

    # -- eviction hook (serving control plane) ----------------------------
    def add_eviction_hook(self, threshold_s: float, callback) -> None:
        """Fire ``callback(rank, lateness_s)`` once per rank when that
        rank SUSTAINS a lateness EWMA >= ``threshold_s``.

        Sustained means the rank stayed the over-threshold straggler
        through ``world`` consecutive evaluations (one evaluation per
        ``observe``), i.e. a full round of fleet reports.  Summaries
        arrive one rank at a time, so mid-round the EWMAs are unevenly
        updated and a shared transient (a recompile spike decaying out)
        makes each rank in turn look late -- the streak requirement
        filters that rotation, a genuinely slow rank keeps the flag
        while everyone else reports.  Callbacks run outside the monitor
        lock (they may call back into :meth:`evict` / :meth:`report`)
        and fire once per rank (latched)."""
        self._evict_hooks.append((float(threshold_s), callback))

    def evict(self, rank: int) -> None:
        """Forget a rank the controller removed from the fleet so the
        lateness attribution tracks the survivors.  The per-rank hook
        latch stays set -- an evicted rank is never re-flagged."""
        with self._lock:
            self._ewma.pop(rank, None)
            self._last_summary.pop(rank, None)
            self._last_seen.pop(rank, None)
            self._warned_stalled.discard(rank)
            for walls in self._steps.values():
                walls.pop(rank, None)
        if self._evict_streak[0] == rank:
            self._evict_streak = (None, 0)

    def _check_eviction(self) -> None:
        if not self._evict_hooks:
            return
        rep = self.report()
        rank = rep["straggler_rank"]
        lateness = float(rep["lateness_s"])
        min_thr = min(t for t, _ in self._evict_hooks)
        if rank is None or lateness < min_thr:
            self._evict_streak = (None, 0)
            return
        prev_rank, streak = self._evict_streak
        streak = streak + 1 if rank == prev_rank else 1
        self._evict_streak = (rank, streak)
        if rank in self._evict_fired or streak < self.world:
            return
        fired = False
        for threshold_s, callback in self._evict_hooks:
            if lateness >= threshold_s:
                fired = True
                try:
                    callback(rank, lateness)
                except Exception:  # hooks must never break the feed
                    logger.exception(
                        "straggler eviction hook failed for rank %d",
                        rank)
        if fired:
            self._evict_fired.add(rank)

    # -- metrics ----------------------------------------------------------
    def _export(self, skew: Optional[float]) -> None:
        try:
            from . import metrics as _metrics
            reg = _metrics.registry()
            rep = self.report()
            if rep["straggler_rank"] is not None:
                reg.gauge(
                    "horovod_straggler_rank",
                    "Rank with the largest step-wall EWMA lateness"
                ).set(float(rep["straggler_rank"]))
                reg.gauge(
                    "horovod_straggler_lateness_seconds",
                    "Straggler's EWMA step wall minus the fastest "
                    "rank's (0 on a single-rank feed)"
                ).set(float(rep["lateness_s"]))
                wall_fam = reg.gauge(
                    "horovod_straggler_rank_wall_seconds",
                    "Per-rank step-wall EWMA as observed by the "
                    "straggler monitor", labelnames=("rank",))
                for r, ewma in rep["per_rank_wall_s"].items():
                    wall_fam.labels(rank=str(r)).set(ewma)
            if skew is not None:
                reg.histogram(
                    "horovod_step_skew_seconds",
                    "Per-step wall-time skew across ranks (slowest "
                    "minus fastest)", buckets=SKEW_BUCKETS
                ).observe(float(skew))
                reg.gauge(
                    "horovod_step_skew_last_seconds",
                    "Most recent per-step cross-rank wall skew"
                ).set(float(skew))
        except Exception:  # metrics must never break the feed
            pass

    def _check_stalled(self, mono: float) -> None:
        if self.stall_check_time <= 0:
            return
        with self._lock:
            stale = [(r, mono - t) for r, t in self._last_seen.items()
                     if mono - t > self.stall_check_time
                     and r not in self._warned_stalled]
            for r, _ in stale:
                self._warned_stalled.add(r)
        for r, age in stale:
            logger.warning(
                "straggler monitor: rank %d has published no step "
                "summary for %.1fs (HOROVOD_STALL_CHECK_TIME_SECONDS="
                "%.0f) -- possible stalled or wedged rank", r, age,
                self.stall_check_time)

    # -- reporting --------------------------------------------------------
    def report(self) -> dict:
        """Current attribution: straggler rank, its lateness, dominant
        span kind, and the latest skew sample."""
        with self._lock:
            if not self._ewma:
                return {"straggler_rank": None, "lateness_s": 0.0,
                        "dominant_span": None, "skew_s": 0.0,
                        "per_rank_wall_s": {}}
            fastest = min(self._ewma.values())
            rank = max(self._ewma, key=lambda r: self._ewma[r])
            lateness = self._ewma[rank] - fastest
            last = self._last_summary.get(rank, {})
            skew = 0.0
            for walls in reversed(self._steps.values()):
                if len(walls) >= 2:
                    skew = max(walls.values()) - min(walls.values())
                    break
            return {
                "straggler_rank": rank,
                "lateness_s": lateness,
                "dominant_span": dominant_span(last),
                "skew_s": skew,
                "per_rank_wall_s": dict(sorted(self._ewma.items())),
            }

    def render(self) -> str:
        """Human-readable one-screen report (the CLI's footer)."""
        rep = self.report()
        if rep["straggler_rank"] is None:
            return "straggler: no observations"
        lines = [
            f"straggler: rank {rep['straggler_rank']} "
            f"(+{rep['lateness_s'] * 1e3:.2f} ms vs fastest, dominant "
            f"span: {rep['dominant_span']}, last skew "
            f"{rep['skew_s'] * 1e3:.2f} ms)"]
        for r, w in rep["per_rank_wall_s"].items():
            marker = "  <-- straggler" if r == rep["straggler_rank"] else ""
            lines.append(f"  rank {r}: ewma {w * 1e3:8.2f} ms{marker}")
        return "\n".join(lines)
