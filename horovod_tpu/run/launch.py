"""``hvdrun``: the launcher CLI (``horovodrun`` analogue).

Reference: ``horovod/runner/launch.py`` (arg surface: ``-np``, hosts,
``--timeline-filename``, ``--autotune``, ``--check-build``, verbosity,
elastic flags) + ``gloo_run.py`` (per-slot env: ``HOROVOD_RANK/SIZE/...``,
rendezvous address, controller selection).

TPU-native inversion: instead of SSH+mpirun fan-out, the launcher starts N
local controller processes (one per host would be one per TPU-pod worker
VM; locally they are test processes) and hands each the JAX coordination
service address (``jax.distributed.initialize``) -- the direct analogue of
the Gloo rendezvous address.  On real multi-host TPU pods, each worker VM's
agent runs the same per-process entry with the coordinator on worker 0.

Usage::

    python -m horovod_tpu.run -np 4 --cpu python train.py --epochs 1
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import threading
from typing import List, Optional

from .exec_util import TaggedProcess, wait_all


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu job: one controller process per "
                    "host/worker, coordinated via the JAX distributed "
                    "runtime.")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="number of controller processes to launch "
                        "(default: total slots of -H/--hostfile, else 1)")
    p.add_argument("-H", "--hosts", default=None,
                   help="comma-separated host[:slots] list (reference "
                        "-H h1:4,h2:4 syntax)")
    p.add_argument("--hostfile", default=None,
                   help="file with one 'host [slots=N]' or host:N per line")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend in workers (testing); each "
                        "worker gets --slots virtual devices")
    p.add_argument("--slots", type=int, default=1,
                   help="devices per worker process in --cpu mode")
    p.add_argument("--coordinator", default="127.0.0.1",
                   help="coordinator host handed to jax.distributed")
    p.add_argument("--coordinator-port", type=int, default=0,
                   help="coordinator port (0 = pick a free one)")
    p.add_argument("--timeline-filename", default=None,
                   help="write a Chrome-trace timeline per rank "
                        "(rank suffix appended)")
    p.add_argument("--timeline-mark-cycles", action="store_true",
                   help="mark scheduler cycles in the timeline "
                        "(HOROVOD_TIMELINE_MARK_CYCLES)")
    p.add_argument("--autotune", action="store_true",
                   help="enable fusion-threshold autotuning in workers")
    p.add_argument("--fusion-threshold-mb", type=int, default=None,
                   help="override HOROVOD_FUSION_THRESHOLD (MiB)")
    p.add_argument("--verbose", "-v", action="count", default=0)
    p.add_argument("--log-level", default=None,
                   choices=("trace", "debug", "info", "warning", "error",
                            "fatal"),
                   help="worker HOROVOD_LOG_LEVEL (overrides -v mapping)")
    p.add_argument("--check-build", action="store_true",
                   help="print build capabilities and exit")
    p.add_argument("--explain-plan", action="store_true",
                   help="render the exchange planner's bucket decision "
                        "for a synthetic parameter set (honours "
                        "HOROVOD_FUSION_THRESHOLD / HOROVOD_COMPRESSION) "
                        "and exit")
    p.add_argument("--no-tag-output", action="store_true",
                   help="do not prefix worker output with [rank]<stream>")
    p.add_argument("--probe", action="store_true",
                   help="pre-launch handshake: every worker slot reports "
                        "its build/runtime versions and the driver fails "
                        "fast on skew (reference driver/task service)")
    # Elastic flags (wired to horovod_tpu.elastic driver).
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None,
                   help="executable printing one host[:slots] per line; "
                        "enables elastic mode")
    p.add_argument("--heartbeat-timeout", type=float, default=None,
                   help="evict elastic workers whose heartbeat file goes "
                        "stale for this many seconds (default: "
                        "HOROVOD_HEARTBEAT_TIMEOUT env or disabled)")
    p.add_argument("--network-rendezvous", action="store_true",
                   help="elastic mode: publish membership + heartbeats "
                        "over the HMAC-signed HTTP KV store instead of a "
                        "shared assignment file (multi-host)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and args to launch per worker")
    return p


def check_build() -> str:
    import jax
    import horovod_tpu
    lines = [
        f"horovod_tpu v{horovod_tpu.__version__}",
        "",
        "Available backends:",
        "    [X] XLA:TPU collectives (ICI/DCN mesh)",
        "    [X] XLA:CPU collectives (gloo, multi-process test backend)",
        "    [ ] NCCL (not applicable: no GPU in the loop)",
        "    [ ] MPI  (not applicable: JAX coordination service instead)",
        "Available features:",
        "    [X] fused allreduce / grouped_allreduce[_async] /",
        "        allgather(+ragged) / broadcast / alltoall /",
        "        reducescatter / barrier / sparse allreduce (torch)",
        "    [X] Adasum (flat + hierarchical dcn x ici)",
        "    [X] fp16/bf16 gradient compression",
        "    [X] autotune (fusion threshold, GP Bayesian)",
        "    [X] timeline (Chrome trace, runtime start/stop)",
        "    [X] elastic (commit/restore + rescale)",
        "    [X] checkpointing (rank-0 npz + orbax sharded)",
        "    [X] sequence parallelism (ring + Ulysses attention)",
        f"jax {jax.__version__}",
    ]
    from ..core.config import detect_tpu_pod
    pod = detect_tpu_pod()
    if pod is not None:
        lines.append(
            f"TPU pod slice detected: worker {pod['rank']}/{pod['size']}, "
            f"coordinator {pod['addr']}:{pod['port']}")
    return "\n".join(lines)


def explain_plan_cli() -> str:
    """``--explain-plan``: render the planner's decision for a synthetic
    ResNet-ish parameter mix (a few big f32 matrices plus small bias
    vectors) under the CONFIGURED threshold and codec -- no ``hvd.init``
    needed, ``plan_buckets`` works uninitialized.  Gives operators a
    zero-setup view of what the exchange stack would decide; pointed at a
    real job, ``fusion.explain_plan(params)`` does the same in-process.
    """
    import jax
    from ..controller import fusion
    from ..core.config import load_config

    cfg = load_config()
    shapes = [(1000, 1000), (512, 512), (4096, 256), (256,), (1000,),
              (64, 3, 7, 7), (512,)]
    leaves = [jax.ShapeDtypeStruct(s, "float32") for s in shapes]
    rows = fusion.explain_plan(leaves,
                               threshold_bytes=cfg.fusion_threshold,
                               compression=cfg.compression,
                               register=False)
    header = (f"# exchange plan: {len(leaves)} synthetic f32 leaves, "
              f"threshold {cfg.fusion_threshold} bytes, "
              f"codec {cfg.compression or 'none'}")
    return header + "\n" + fusion.render_plan(rows)


def run_command(args: Optional[List[str]] = None) -> int:
    parser = build_parser()
    opts = parser.parse_args(args)
    if opts.check_build:
        print(check_build())
        return 0
    if opts.explain_plan:
        print(explain_plan_cli())
        return 0

    if opts.timeline_mark_cycles and not (
            opts.timeline_filename or os.environ.get("HOROVOD_TIMELINE")
            or os.environ.get("HVD_TPU_TIMELINE")):
        print("# warning: --timeline-mark-cycles has no effect without "
              "--timeline-filename (or HOROVOD_TIMELINE)", file=sys.stderr)

    cmd = list(opts.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given")

    np_ = opts.num_proc
    if opts.hosts or opts.hostfile:
        if opts.host_discovery_script:
            parser.error("-H/--hostfile is a static host list; it cannot "
                         "be combined with --host-discovery-script "
                         "(elastic membership comes from the script)")
        from .hosts import (all_local, parse_host_spec, parse_hostfile,
                            total_slots)
        try:
            hosts = parse_host_spec(opts.hosts) if opts.hosts else \
                parse_hostfile(opts.hostfile)
        except (ValueError, OSError) as e:
            parser.error(str(e))
        if not all_local(hosts):
            parser.error(
                "remote hosts in -H/--hostfile: this launcher spawns "
                "processes locally (on TPU pods each worker VM's agent "
                "runs `hvdrun` with its local slots; point every VM at "
                "the same --coordinator and use HOROVOD_RANK offsets). "
                f"Got: {', '.join(h for h, _ in hosts)}")
        if np_ is None:
            np_ = total_slots(hosts)
    elif np_ is None and not opts.host_discovery_script:
        # No explicit -np/-H: inside an LSF allocation, derive the process
        # count from the scheduler like the reference's horovodrun does
        # (util/lsf.py).  An explicit -np always wins, so per-VM launches
        # with a shared --coordinator stay possible on multi-host jobs.
        from .lsf import get_compute_hosts, using_lsf
        if using_lsf():
            from .hosts import all_local, total_slots
            try:
                hosts = get_compute_hosts()
            except ValueError as e:
                parser.error(str(e))
            if not all_local(hosts):
                parser.error(
                    "LSF allocation spans multiple hosts: run hvdrun on "
                    "each worker VM with -np <local slots> and a shared "
                    "--coordinator. Hosts: "
                    f"{', '.join(h for h, _ in hosts)}")
            np_ = total_slots(hosts)
    if np_ is None:
        np_ = 1
    if opts.host_discovery_script:
        from ..core.config import load_config
        from ..elastic.driver import ElasticDriver
        heartbeat = opts.heartbeat_timeout
        if heartbeat is None:
            heartbeat = load_config().heartbeat_timeout
        # Per-worker env flags ride extra_env so elastic workers honor
        # the same CLI surface as the static spawn loop (the per-rank
        # timeline suffix is applied at each spawn).
        extra = {}
        if opts.log_level:
            extra["HOROVOD_LOG_LEVEL"] = opts.log_level
        elif opts.verbose:
            extra["HOROVOD_LOG_LEVEL"] = ("debug" if opts.verbose > 1
                                          else "info")
        if opts.autotune:
            extra["HOROVOD_AUTOTUNE"] = "1"
        if opts.fusion_threshold_mb is not None:
            extra["HOROVOD_FUSION_THRESHOLD"] = str(
                opts.fusion_threshold_mb << 20)
        if opts.timeline_filename:
            extra["HOROVOD_TIMELINE"] = opts.timeline_filename
        if opts.timeline_mark_cycles:
            extra["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
        driver = ElasticDriver(
            command=cmd,
            discovery_script=opts.host_discovery_script,
            min_np=opts.min_np or 1,
            max_np=opts.max_np,
            cpu=opts.cpu,
            slots=opts.slots,
            verbose=opts.verbose,
            heartbeat_timeout_s=heartbeat,
            rendezvous=opts.network_rendezvous,
            extra_env=extra,
        )
        return driver.run()

    if opts.probe:
        from .probe import DriverProbe
        probe = DriverProbe()
        wids = [f"slot{r}" for r in range(np_)]
        procs_ = [probe.spawn_local_probe(w) for w in wids]
        try:
            reports = probe.collect(wids)
            probe.validate(reports)
            if opts.verbose:
                for w, r in reports.items():
                    print(f"# probe {w}: {r['hostname']} "
                          f"hvd={r['framework_version']} "
                          f"jax={r['jax_version']}")
        finally:
            # Reap best-effort: a hung probe child must not mask the real
            # collect/validate error or leak the rendezvous server.
            for pr in procs_:
                try:
                    pr.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    pr.kill()
            probe.stop()

    port = opts.coordinator_port or free_port()
    lock = threading.Lock()
    procs: List[TaggedProcess] = []
    for rank in range(np_):
        env = dict(os.environ)
        env.update(worker_env(
            rank=rank, size=np_, coordinator=opts.coordinator, port=port,
            cpu=opts.cpu, slots=opts.slots))
        apply_timeline_env(env, rank, opts.timeline_filename)
        if opts.timeline_mark_cycles:
            # The timeline may come from the CLI flag or inherited env;
            # config ignores mark-cycles when no timeline is active.
            env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
        if opts.autotune:
            env["HOROVOD_AUTOTUNE"] = "1"
        if opts.fusion_threshold_mb is not None:
            env["HOROVOD_FUSION_THRESHOLD"] = str(
                opts.fusion_threshold_mb << 20)
        if opts.log_level:
            env["HOROVOD_LOG_LEVEL"] = opts.log_level
        elif opts.verbose:
            env["HOROVOD_LOG_LEVEL"] = "debug" if opts.verbose > 1 else "info"
        procs.append(TaggedProcess(rank, cmd, env, lock=lock,
                                   tag=not opts.no_tag_output))
    return wait_all(procs)


def apply_timeline_env(env: dict, suffix,
                       cli_filename: Optional[str] = None) -> None:
    """Point this worker's timeline at a per-rank file.

    A shared path would have every worker ``open(path, 'w')`` the SAME
    file and interleave/truncate each other's trace.  The CLI flag wins
    (and clears any inherited spelling, since config resolves HVD_TPU_
    first); otherwise inherited HOROVOD_TIMELINE/HVD_TPU_TIMELINE values
    get the suffix.  The static spawn loop suffixes by rank; the elastic
    driver by the STABLE worker id (ranks are reassigned on rescale).
    """
    if cli_filename:
        env.pop("HVD_TPU_TIMELINE", None)
        env["HOROVOD_TIMELINE"] = f"{cli_filename}.{suffix}"
        return
    for var in ("HOROVOD_TIMELINE", "HVD_TPU_TIMELINE"):
        if env.get(var):
            env[var] = f"{env[var]}.{suffix}"


def worker_env(rank: int, size: int, coordinator: str, port: int,
               cpu: bool, slots: int = 1, local_rank: Optional[int] = None,
               local_size: Optional[int] = None) -> dict:
    """Per-worker environment (the gloo_run per-slot env analogue)."""
    env = {
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank if local_rank is not None
                                  else rank),
        "HOROVOD_LOCAL_SIZE": str(local_size if local_size is not None
                                  else size),
        "HOROVOD_CROSS_RANK": "0",
        "HOROVOD_CROSS_SIZE": "1",
        "HVD_TPU_COORDINATOR_ADDR": coordinator,
        "HVD_TPU_COORDINATOR_PORT": str(port),
    }
    if cpu:
        from ..utils.platform import set_host_device_flag
        env["HVD_TPU_FORCE_CPU"] = "1"
        env["XLA_FLAGS"] = set_host_device_flag(
            os.environ.get("XLA_FLAGS", ""), slots)
    return env


def main() -> None:  # console entry
    sys.exit(run_command())
