"""Shared-secret HMAC envelope (reference
``horovod/runner/common/util/secret.py``).

Every rendezvous/notification message between launcher and workers is
signed with a per-job secret so a stray process on the same network
segment cannot impersonate the driver.  The secret travels only through
the worker environment (the launcher sets it when spawning), never over
the wire.
"""

from __future__ import annotations

import hmac
import hashlib
import secrets as _secrets

SECRET_ENV = "HVD_TPU_SECRET_KEY"
DIGEST = hashlib.sha256


def make_secret_key() -> str:
    """New per-job secret (hex, 256-bit)."""
    return _secrets.token_hex(32)


def compute_digest(secret_key: str, payload: bytes) -> str:
    return hmac.new(secret_key.encode(), payload, DIGEST).hexdigest()


def check_digest(secret_key: str, payload: bytes, digest: str) -> bool:
    want = compute_digest(secret_key, payload)
    return hmac.compare_digest(want, digest)
