"""HMAC-signed HTTP KV store: the rendezvous plane.

Reference: ``horovod/runner/http/http_server.py`` (``RendezvousServer``, a
threaded HTTP KV store used by Gloo rendezvous and elastic worker
registration) + ``http_client.py``.  TPU-native role: the launcher/elastic
driver publishes the membership document (epoch, coordinator port, rank
assignment) under a key; workers on other VMs poll it over HTTP instead of
a shared-filesystem assignment file.  Every request is HMAC-signed with
the per-job secret (``run/secret.py``); unsigned or mis-signed requests
get 403.

Wire format: ``PUT/GET/DELETE /kv/<scope>/<key>``; the ``X-Hvd-Sig``
header signs ``method\\npath\\ntimestamp\\nbody`` and the ``X-Hvd-Ts``
timestamp must be within ``MAX_SKEW_S`` of the server clock, bounding the
replay window.  Auth failures raise :class:`RendezvousAuthError` (NOT a
``ConnectionError``): a wrong per-job secret is a configuration bug that
must surface loudly, while connection errors mean the driver is
down/restarting and are retried by callers.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.request import Request, urlopen
from urllib.error import HTTPError, URLError

from .secret import check_digest, compute_digest

SIG_HEADER = "X-Hvd-Sig"
TS_HEADER = "X-Hvd-Ts"
MAX_SKEW_S = 60.0


class RendezvousAuthError(RuntimeError):
    """Signature rejected (wrong or missing per-job secret)."""


def _signable(method: str, path: str, ts: str, body: bytes) -> bytes:
    return (method.encode() + b"\n" + path.encode() + b"\n" + ts.encode()
            + b"\n" + body)


class RendezvousServer:
    """Threaded KV store over HTTP; values are opaque bytes."""

    def __init__(self, secret_key: str, host: str = "127.0.0.1",
                 port: int = 0):
        # Default loopback: the local driver hands workers 127.0.0.1.
        # Multi-host deployments pass host="0.0.0.0" explicitly.
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        store, lock, secret = self._store, self._lock, secret_key

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _verify(self, body: bytes) -> bool:
                import time
                sig = self.headers.get(SIG_HEADER, "")
                ts = self.headers.get(TS_HEADER, "")
                try:
                    skew = abs(time.time() - float(ts))
                except ValueError:
                    return False
                if skew > MAX_SKEW_S:
                    return False
                return check_digest(
                    secret,
                    _signable(self.command, self.path, ts, body), sig)

            def _reply(self, code: int, body: bytes = b"") -> None:
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if not self._verify(b""):
                    return self._reply(403)
                with lock:
                    val = store.get(self.path)
                self._reply(200, val) if val is not None else self._reply(404)

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if not self._verify(body):
                    return self._reply(403)
                with lock:
                    store[self.path] = body
                self._reply(200)

            def do_DELETE(self):
                if not self._verify(b""):
                    return self._reply(403)
                with lock:
                    store.pop(self.path, None)
                self._reply(200)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="hvd-tpu-rendezvous")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class KVClient:
    """Signing client for :class:`RendezvousServer`."""

    def __init__(self, addr: str, port: int, secret_key: str,
                 timeout_s: float = 10.0):
        self.base = f"http://{addr}:{port}"
        self.secret_key = secret_key
        self.timeout_s = timeout_s

    @classmethod
    def from_url(cls, url: str, secret_key: str,
                 timeout_s: float = 10.0) -> "KVClient":
        """``http://host:port`` -> client."""
        hostport = url.split("//", 1)[1].rstrip("/")
        host, _, port = hostport.rpartition(":")
        return cls(host, int(port), secret_key, timeout_s)

    def _request(self, method: str, path: str,
                 body: bytes = b"") -> Tuple[int, bytes]:
        import time
        ts = repr(time.time())
        sig = compute_digest(self.secret_key,
                             _signable(method, path, ts, body))
        req = Request(self.base + path, data=body if method == "PUT" else
                      None, method=method,
                      headers={SIG_HEADER: sig, TS_HEADER: ts})
        try:
            with urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, resp.read()
        except HTTPError as e:
            return e.code, b""
        except (URLError, TimeoutError, OSError) as e:
            # Normalize every transport failure to ConnectionError so
            # callers' "driver down/restarting, retry" handling sees one
            # type (urllib raises URLError/TimeoutError, not
            # ConnectionError).
            raise ConnectionError(
                f"rendezvous {method} {path}: {e}") from e

    def _check(self, op: str, code: int) -> None:
        if code == 403:
            raise RendezvousAuthError(
                f"rendezvous {op} rejected (403): per-job secret mismatch "
                f"or >={MAX_SKEW_S:.0f}s clock skew -- check "
                "HVD_TPU_SECRET_KEY and NTP on every host")
        if code != 200:
            raise ConnectionError(f"rendezvous {op} -> HTTP {code}")

    def put(self, scope: str, key: str, value: bytes) -> None:
        code, _ = self._request("PUT", f"/kv/{scope}/{key}", value)
        self._check(f"PUT {scope}/{key}", code)

    def get(self, scope: str, key: str) -> Optional[bytes]:
        code, body = self._request("GET", f"/kv/{scope}/{key}")
        if code == 200:
            return body
        if code == 404:
            return None
        self._check(f"GET {scope}/{key}", code)

    def delete(self, scope: str, key: str) -> None:
        code, _ = self._request("DELETE", f"/kv/{scope}/{key}")
        self._check(f"DELETE {scope}/{key}", code)
