"""HMAC-signed HTTP KV store: the rendezvous plane.

Reference: ``horovod/runner/http/http_server.py`` (``RendezvousServer``, a
threaded HTTP KV store used by Gloo rendezvous and elastic worker
registration) + ``http_client.py``.  TPU-native role: the launcher/elastic
driver publishes the membership document (epoch, coordinator port, rank
assignment) under a key; workers on other VMs poll it over HTTP instead of
a shared-filesystem assignment file.  Every request is HMAC-signed with
the per-job secret (``run/secret.py``); unsigned or mis-signed requests
get 403.

Wire format: ``PUT/GET/DELETE /kv/<scope>/<key>``; the ``X-Hvd-Sig``
header signs ``method\\npath\\ntimestamp\\nbody`` and the ``X-Hvd-Ts``
timestamp must be within ``MAX_SKEW_S`` of the server clock, bounding the
replay window.  Auth failures raise :class:`RendezvousAuthError` (NOT a
``ConnectionError``): a wrong per-job secret is a configuration bug that
must surface loudly, while connection errors mean the driver is
down/restarting and are retried by callers.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.request import Request, urlopen
from urllib.error import HTTPError, URLError

from .retry import RetryPolicy, call_with_retries
from .secret import check_digest, compute_digest

SIG_HEADER = "X-Hvd-Sig"
TS_HEADER = "X-Hvd-Ts"
MAX_SKEW_S = 60.0


class RendezvousAuthError(RuntimeError):
    """Signature rejected (wrong or missing per-job secret)."""


def _signable(method: str, path: str, ts: str, body: bytes) -> bytes:
    return (method.encode() + b"\n" + path.encode() + b"\n" + ts.encode()
            + b"\n" + body)


class RendezvousServer:
    """Threaded KV store over HTTP; values are opaque bytes."""

    def __init__(self, secret_key: str, host: str = "127.0.0.1",
                 port: int = 0):
        # Default loopback: the local driver hands workers 127.0.0.1.
        # Multi-host deployments pass host="0.0.0.0" explicitly.
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        # Monotonic deadline before which every request gets 503: the
        # chaos harness uses this to simulate a driver outage that the
        # client-side retry policy must ride out.
        self._blackout_until = 0.0
        server = self
        store, lock, secret = self._store, self._lock, secret_key

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _blacked_out(self) -> bool:
                import time
                return time.monotonic() < server._blackout_until

            def _verify(self, body: bytes) -> bool:
                import time
                sig = self.headers.get(SIG_HEADER, "")
                ts = self.headers.get(TS_HEADER, "")
                try:
                    skew = abs(time.time() - float(ts))
                except ValueError:
                    return False
                if skew > MAX_SKEW_S:
                    return False
                return check_digest(
                    secret,
                    _signable(self.command, self.path, ts, body), sig)

            def _reply(self, code: int, body: bytes = b"") -> None:
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self._blacked_out():
                    return self._reply(503)
                if not self._verify(b""):
                    return self._reply(403)
                if self.path == "/time":
                    # NTP-style clock reference for the trace plane
                    # (timeline/sync.py): the instant the reply is built
                    # is the server-clock sample; signed like every
                    # other KV request.
                    import time
                    return self._reply(200, repr(time.time()).encode())
                with lock:
                    val = store.get(self.path)
                self._reply(200, val) if val is not None else self._reply(404)

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if self._blacked_out():
                    return self._reply(503)
                if not self._verify(body):
                    return self._reply(403)
                with lock:
                    store[self.path] = body
                self._reply(200)

            def do_DELETE(self):
                if self._blacked_out():
                    return self._reply(503)
                if not self._verify(b""):
                    return self._reply(403)
                with lock:
                    store.pop(self.path, None)
                self._reply(200)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="hvd-tpu-rendezvous")
        self._thread.start()

    def blackout(self, secs: float) -> None:
        """Refuse every request with 503 for ``secs`` seconds (fault
        injection: simulated driver outage)."""
        import time
        self._blackout_until = time.monotonic() + max(0.0, secs)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class KVClient:
    """Signing client for :class:`RendezvousServer`."""

    def __init__(self, addr: str, port: int, secret_key: str,
                 timeout_s: float = 10.0,
                 retry_policy: Optional[RetryPolicy] = None):
        self.base = f"http://{addr}:{port}"
        self.secret_key = secret_key
        self.timeout_s = timeout_s
        # One env-tuned policy for every KV caller (workers, driver
        # heartbeats, notify): HOROVOD_KV_RETRIES / HOROVOD_KV_BACKOFF_MS.
        self.retry_policy = (retry_policy if retry_policy is not None
                             else RetryPolicy.from_env())

    @classmethod
    def from_url(cls, url: str, secret_key: str,
                 timeout_s: float = 10.0,
                 retry_policy: Optional[RetryPolicy] = None) -> "KVClient":
        """``http://host:port`` -> client."""
        hostport = url.split("//", 1)[1].rstrip("/")
        host, _, port = hostport.rpartition(":")
        return cls(host, int(port), secret_key, timeout_s,
                   retry_policy=retry_policy)

    def _request(self, method: str, path: str,
                 body: bytes = b"") -> Tuple[int, bytes]:
        import time
        try:
            from ..elastic import chaos as _chaos
        except ImportError:  # partial install without the elastic package
            _chaos = None
        if _chaos is not None and _chaos.kv_blackout_active():
            raise ConnectionError(
                f"rendezvous {method} {path}: chaos KV blackout")
        ts = repr(time.time())
        sig = compute_digest(self.secret_key,
                             _signable(method, path, ts, body))
        req = Request(self.base + path, data=body if method == "PUT" else
                      None, method=method,
                      headers={SIG_HEADER: sig, TS_HEADER: ts})
        try:
            with urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, resp.read()
        except HTTPError as e:
            return e.code, b""
        except (URLError, TimeoutError, OSError) as e:
            # Normalize every transport failure to ConnectionError so
            # callers' "driver down/restarting, retry" handling sees one
            # type (urllib raises URLError/TimeoutError, not
            # ConnectionError).
            raise ConnectionError(
                f"rendezvous {method} {path}: {e}") from e

    def _check(self, op: str, code: int) -> None:
        if code == 403:
            raise RendezvousAuthError(
                f"rendezvous {op} rejected (403): per-job secret mismatch "
                f"or >={MAX_SKEW_S:.0f}s clock skew -- check "
                "HVD_TPU_SECRET_KEY and NTP on every host")
        if code != 200:
            raise ConnectionError(f"rendezvous {op} -> HTTP {code}")

    def _retrying(self, fn, describe: str):
        # RendezvousAuthError subclasses RuntimeError, not
        # ConnectionError, so a bad secret surfaces on the first attempt;
        # transport failures (already normalized to ConnectionError by
        # _request) and non-200 statuses burn the backoff budget.
        return call_with_retries(fn, policy=self.retry_policy,
                                 retry_on=(ConnectionError,),
                                 no_retry=(RendezvousAuthError,),
                                 describe=describe)

    def put(self, scope: str, key: str, value: bytes) -> None:
        def _once() -> None:
            code, _ = self._request("PUT", f"/kv/{scope}/{key}", value)
            self._check(f"PUT {scope}/{key}", code)
        self._retrying(_once, f"kv PUT {scope}/{key}")

    def get(self, scope: str, key: str) -> Optional[bytes]:
        def _once() -> Optional[bytes]:
            code, body = self._request("GET", f"/kv/{scope}/{key}")
            if code == 200:
                return body
            if code == 404:
                return None
            self._check(f"GET {scope}/{key}", code)
        return self._retrying(_once, f"kv GET {scope}/{key}")

    def delete(self, scope: str, key: str) -> None:
        def _once() -> None:
            code, _ = self._request("DELETE", f"/kv/{scope}/{key}")
            self._check(f"DELETE {scope}/{key}", code)
        self._retrying(_once, f"kv DELETE {scope}/{key}")

    # -- chunked bulk transfer (KV-page streaming) -------------------------
    #
    # A prompt's K/V pages are megabytes; one PUT of the whole payload
    # ties a request thread up for the full transfer and makes a mid-
    # stream failure all-or-nothing.  put_large splits the value into
    # fixed-size parts at ``<key>.part<i>`` and writes a tiny manifest
    # at ``<key>`` LAST, so a reader either sees no manifest (write in
    # flight or dead) or a complete, hash-verified object -- the same
    # commit-point discipline as the membership document.  Each part
    # PUT/GET rides the client's RetryPolicy independently, so a driver
    # blackout in the middle of a stream is survived per-chunk.

    MANIFEST_MAGIC = "HVDL1"
    CHUNK_BYTES = 1 << 20

    def put_large(self, scope: str, key: str, value: bytes,
                  chunk_bytes: int = 0) -> int:
        """Chunked binary-safe PUT; returns the number of parts."""
        import hashlib
        import json
        cb = int(chunk_bytes) or self.CHUNK_BYTES
        parts = max(1, -(-len(value) // cb))  # ceil; empty value = 1 part
        for i in range(parts):
            self.put(scope, f"{key}.part{i}", value[i * cb:(i + 1) * cb])
        manifest = json.dumps({
            "v": self.MANIFEST_MAGIC, "parts": parts,
            "bytes": len(value), "chunk_bytes": cb,
            "sha256": hashlib.sha256(value).hexdigest()},
            sort_keys=True).encode()
        self.put(scope, key, manifest)
        return parts

    def get_large(self, scope: str, key: str) -> Optional[bytes]:
        """Chunked GET: None until the manifest commits; a committed
        manifest whose parts are missing, short, or hash-mismatched
        raises ``ValueError`` (torn or corrupted object)."""
        import hashlib
        import json
        raw = self.get(scope, key)
        if raw is None:
            return None
        try:
            m = json.loads(raw)
            ok = m.get("v") == self.MANIFEST_MAGIC
        except (ValueError, AttributeError):
            ok = False
        if not ok:
            raise ValueError(
                f"kv {scope}/{key}: not a chunked-object manifest")
        chunks = []
        for i in range(int(m["parts"])):
            part = self.get(scope, f"{key}.part{i}")
            if part is None:
                raise ValueError(
                    f"kv {scope}/{key}: manifest committed but part {i} "
                    f"of {m['parts']} is missing")
            chunks.append(part)
        value = b"".join(chunks)
        if len(value) != int(m["bytes"]):
            raise ValueError(
                f"kv {scope}/{key}: reassembled {len(value)} byte(s), "
                f"manifest promises {m['bytes']}")
        if hashlib.sha256(value).hexdigest() != m["sha256"]:
            raise ValueError(
                f"kv {scope}/{key}: content hash mismatch after "
                "reassembly")
        return value

    def delete_large(self, scope: str, key: str) -> None:
        """Delete manifest FIRST (readers stop seeing the object), then
        the parts."""
        import json
        raw = self.get(scope, key)
        parts = 0
        if raw is not None:
            try:
                m = json.loads(raw)
                if m.get("v") == self.MANIFEST_MAGIC:
                    parts = int(m["parts"])
            except (ValueError, AttributeError):
                parts = 0
        self.delete(scope, key)
        for i in range(parts):
            self.delete(scope, f"{key}.part{i}")

    def server_time(self) -> float:
        """The KV server's wall clock (seconds since the epoch), for
        NTP-style offset estimation (``timeline/sync.py``).  Retried
        like every other KV call; auth failures surface immediately."""
        def _once() -> float:
            code, body = self._request("GET", "/time")
            self._check("GET /time", code)
            return float(body)
        return self._retrying(_once, "kv GET /time")
