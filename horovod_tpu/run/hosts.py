"""Host-list parsing (reference ``horovod/runner/common/util/hosts.py``).

``horovodrun -H h1:4,h2:4`` / ``--hostfile`` name the worker VMs and their
slot counts.  On TPU pods a "slot" is a worker VM's process (the per-VM
agent runs one controller process per host), so slots default to 1 rather
than the reference's GPU count.

:func:`split_host_slots` is the one canonical ``host[:slots]`` splitter
(IPv6-aware); elastic discovery shares it in lenient mode.
"""

from __future__ import annotations

from typing import List, Tuple

LOCAL_ALIASES = ("localhost", "127.0.0.1", "::1")


class _NotSlots(Exception):
    """Lenient-mode signal: the suffix was not a slot count."""


def _parse_slots(text: str, item: str, strict: bool,
                 default_slots: int) -> int:
    try:
        n = int(text)
    except ValueError:
        if strict:
            raise ValueError(f"bad host spec {item!r}: slots must be an "
                             f"integer (host[:slots])")
        raise _NotSlots()
    if n < 1:
        if strict:
            raise ValueError(f"bad host spec {item!r}: slots must be >= 1")
        # Lenient (elastic discovery): "host:0" means a DRAINED host --
        # zero slots removes its workers; it must not be reparsed as a
        # phantom hostname with default slots.
        return max(n, 0)
    return n


def split_host_slots(item: str, default_slots: int = 1,
                     strict: bool = False) -> Tuple[str, int]:
    """``host | host:slots | [ipv6] | [ipv6]:slots`` -> ``(host, slots)``.

    A bare IPv6 address (two or more colons, e.g. ``::1``) is a host with
    default slots; only a single-colon suffix (or the bracketed form)
    carries a slot count.  ``strict=True`` raises on malformed input;
    lenient mode (elastic discovery) falls back to the default.
    """
    if item.startswith("["):
        addr, _, rest = item.partition("]")
        host = addr[1:]
        if not host:
            if strict:
                raise ValueError(f"bad host spec {item!r}: empty host")
            return item, default_slots
        if rest.startswith(":"):
            try:
                return host, _parse_slots(rest[1:], item, strict,
                                          default_slots)
            except _NotSlots:
                return item, default_slots
        if rest and strict:
            raise ValueError(f"bad host spec {item!r}: junk after ']'")
        return host, default_slots
    if item.count(":") == 1:
        host, _, slots = item.partition(":")
        if not host:
            if strict:
                raise ValueError(f"bad host spec {item!r}: empty host")
            return item, default_slots
        try:
            return host, _parse_slots(slots, item, strict, default_slots)
        except _NotSlots:
            # Lenient: a non-count suffix means the colon is part of the
            # hostname ("host:gpu" stays one opaque host token).
            return item, default_slots
    return item, default_slots


def parse_host_spec(spec: str, default_slots: int = 1
                    ) -> List[Tuple[str, int]]:
    """``"h1:4,h2:4,h3"`` -> ``[("h1", 4), ("h2", 4), ("h3", 1)]``."""
    out: List[Tuple[str, int]] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        out.append(split_host_slots(item, default_slots, strict=True))
    if not out:
        raise ValueError(f"no hosts in spec {spec!r}")
    return out


def parse_hostfile(path: str, default_slots: int = 1
                   ) -> List[Tuple[str, int]]:
    """One ``host [slots=N | :N]`` per line; ``#`` comments allowed.

    Accepts both the reference's hostfile dialect (``host slots=N``, the
    mpirun convention) and the compact ``host:N``.  Slot counts are
    validated like ``-H`` (integer, >= 1).
    """
    out: List[Tuple[str, int]] = []
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            host, n = split_host_slots(parts[0], default_slots, strict=True)
            for p in parts[1:]:
                if p.startswith("slots="):
                    n = _parse_slots(p[len("slots="):], line, True,
                                     default_slots)
            out.append((host, n))
    if not out:
        raise ValueError(f"hostfile {path!r} has no hosts")
    return out


def total_slots(hosts: List[Tuple[str, int]]) -> int:
    return sum(n for _, n in hosts)


def all_local(hosts: List[Tuple[str, int]]) -> bool:
    import socket
    local = set(LOCAL_ALIASES) | {socket.gethostname()}
    return all(h in local for h, _ in hosts)
