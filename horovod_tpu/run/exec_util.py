"""Subprocess execution with rank-tagged output streaming.

Analogue of the reference launcher's ``safe_shell_exec`` + stream
multiplexing (``horovod/runner/common/util/safe_shell_exec.py`` /
``util/streams``): every worker's stdout/stderr is forwarded line-by-line
to the launcher's streams prefixed ``[rank]<stdout>`` so interleaved
multi-process logs stay attributable.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence


def _pump(stream, out, prefix: str, lock: threading.Lock) -> None:
    for line in iter(stream.readline, b""):
        with lock:
            out.write(f"{prefix}{line.decode(errors='replace')}")
            out.flush()
    stream.close()


class TaggedProcess:
    """A worker subprocess whose output is forwarded with a rank tag."""

    def __init__(self, rank: int, cmd: Sequence[str], env: Dict[str, str],
                 lock: Optional[threading.Lock] = None, tag: bool = True):
        self.rank = rank
        self.proc = subprocess.Popen(
            list(cmd), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, start_new_session=True)
        lock = lock or threading.Lock()
        p_out = f"[{rank}]<stdout>" if tag else ""
        p_err = f"[{rank}]<stderr>" if tag else ""
        self._threads = [
            threading.Thread(target=_pump, daemon=True,
                             args=(self.proc.stdout, sys.stdout, p_out, lock)),
            threading.Thread(target=_pump, daemon=True,
                             args=(self.proc.stderr, sys.stderr, p_err, lock)),
        ]
        for t in self._threads:
            t.start()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            code = self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None
        for t in self._threads:
            t.join(timeout=5)
        return code

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def terminate(self) -> None:
        """SIGTERM the worker's whole process group."""
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass

    def kill(self) -> None:
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass


def wait_all(procs: List[TaggedProcess], poll_s: float = 0.2,
             term_grace_s: float = 15.0) -> int:
    """Wait for all workers; on first failure terminate the rest, escalating
    to SIGKILL after a grace period (a peer wedged in a blocking collective
    may ignore SIGTERM).

    Returns the first non-zero exit code, or 0.  Mirrors the reference
    launcher's all-or-nothing process supervision.
    """
    import time
    pending = list(procs)
    first_bad = 0
    kill_deadline = None
    while pending:
        for p in list(pending):
            code = p.poll()
            if code is None:
                continue
            pending.remove(p)
            p.wait()
            if code != 0 and first_bad == 0:
                first_bad = code
                kill_deadline = time.monotonic() + term_grace_s
                for other in pending:
                    other.terminate()
        if kill_deadline is not None and time.monotonic() > kill_deadline:
            for p in pending:
                p.kill()
            kill_deadline = None
        if pending:
            pending[0].wait(timeout=poll_s)
    return first_bad
