"""Pre-launch driver/task probe (reference ``runner/driver_service.py`` +
``task_service.py`` handshake).

Before fanning out workers, the reference's launcher spawns a small task
service on every host to (a) verify each host runs a compatible build and
(b) discover mutually-routable interfaces.  TPU-native version: each task
probe reports hostname, framework/jax versions, and the addresses it can
serve on, over the HMAC-signed KV plane; the driver collects the reports
and fails fast on version skew -- the reference's "same Horovod build
everywhere" check, which otherwise surfaces hours later as a hanging
collective.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from .http_kv import KVClient, RendezvousServer
from .secret import SECRET_ENV, make_secret_key

PROBE_SCOPE = "probe"


def probe_report() -> dict:
    """What one task probe reports (runs on the worker host)."""
    import jax

    import horovod_tpu

    return {
        "hostname": socket.gethostname(),
        "framework_version": horovod_tpu.__version__,
        "jax_version": jax.__version__,
        "python": "%d.%d" % sys.version_info[:2],
        "addresses": _local_addresses(),
    }


def _local_addresses() -> List[str]:
    addrs = {"127.0.0.1"}
    try:
        host = socket.gethostname()
        for info in socket.getaddrinfo(host, None):
            addrs.add(info[4][0])
    except OSError:
        pass
    return sorted(addrs)


def run_task_probe(worker_id: str, url: str, secret: str) -> None:
    """Task side: publish this host's report."""
    kv = KVClient.from_url(url, secret)
    kv.put(PROBE_SCOPE, worker_id, json.dumps(probe_report()).encode())


def _probe_main() -> int:  # python -m horovod_tpu.run.probe <wid> <url>
    run_task_probe(sys.argv[1], sys.argv[2], os.environ[SECRET_ENV])
    return 0


class DriverProbe:
    """Driver side: collect per-host reports and validate compatibility."""

    def __init__(self, secret: Optional[str] = None):
        self.secret = secret or make_secret_key()
        self._server = RendezvousServer(self.secret)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self._server.port}"

    def spawn_local_probe(self, worker_id: str) -> subprocess.Popen:
        env = dict(os.environ)
        env[SECRET_ENV] = self.secret
        return subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.run.probe", worker_id,
             self.url], env=env)

    def collect(self, worker_ids: List[str],
                timeout_s: float = 60.0) -> Dict[str, dict]:
        """Wait for every probe's report; raises on timeout."""
        kv = KVClient.from_url(self.url, self.secret)
        reports: Dict[str, dict] = {}
        deadline = time.monotonic() + timeout_s
        while len(reports) < len(worker_ids):
            if time.monotonic() > deadline:
                missing = [w for w in worker_ids if w not in reports]
                raise TimeoutError(
                    f"no probe report from {missing} within {timeout_s}s")
            for wid in worker_ids:
                if wid in reports:
                    continue
                raw = kv.get(PROBE_SCOPE, wid)
                if raw is not None:
                    reports[wid] = json.loads(raw)
            time.sleep(0.1)
        return reports

    def validate(self, reports: Dict[str, dict]) -> None:
        """Fail fast on build skew (reference same-build check)."""
        for field in ("framework_version", "jax_version", "python"):
            values = {r[field] for r in reports.values()}
            if len(values) > 1:
                detail = {w: r[field] for w, r in reports.items()}
                raise RuntimeError(
                    f"incompatible worker environments: {field} differs "
                    f"across hosts: {detail} -- a mixed-build job would "
                    "fail mid-run with hanging collectives")

    def stop(self) -> None:
        self._server.stop()


if __name__ == "__main__":
    sys.exit(_probe_main())
