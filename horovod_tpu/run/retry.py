"""Unified transport retry policy for the rendezvous/control plane.

One policy object governs every HTTP KV interaction (``run/http_kv.py``),
driver heartbeat writes, and assignment reads in ``elastic/notify.py``:
exponential backoff with full jitter and a bounded retry budget, tuned by
``HOROVOD_KV_RETRIES`` (extra attempts after the first, default 3) and
``HOROVOD_KV_BACKOFF_MS`` (initial delay, default 50ms).

Reference: ``horovod/runner/http/http_client.py`` retries PUT/GET against
the Gloo rendezvous server a fixed number of times with a flat sleep; the
TPU-native plane upgrades that to capped exponential backoff + jitter so a
driver restart (seconds) is survived without hammering the KV endpoint,
while a wrong secret (``RendezvousAuthError``) still fails on the first
attempt -- auth failures are configuration bugs and are never retried.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type, TypeVar

from ..core.config import _env_float, _env_int

import logging

logger = logging.getLogger("horovod_tpu.run")

T = TypeVar("T")


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with full jitter.

    ``retries`` is the budget of *additional* attempts after the first
    (``retries=0`` disables retrying entirely); attempt ``i`` sleeps
    ``min(backoff_ms * multiplier**i, max_backoff_ms)`` scaled by a
    uniform jitter factor in ``[1 - jitter, 1]``.
    """

    retries: int = 3
    backoff_ms: float = 50.0
    multiplier: float = 2.0
    max_backoff_ms: float = 2000.0
    jitter: float = 0.5
    # Total backoff-sleep budget in seconds across ALL attempts of one
    # call (None = unbounded, the pre-PR-20 behavior).  Bulk transfers
    # -- a multi-MiB KV-page stream is many chunked PUTs, each with its
    # own retry loop -- use this to cap worst-case stall per chunk so a
    # dead peer fails the handoff in bounded time instead of
    # retries * max_backoff per chunk.
    budget_s: Optional[float] = None

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(retries=_env_int("KV_RETRIES", 3),
                   backoff_ms=_env_float("KV_BACKOFF_MS", 50.0))

    def delay_s(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        base = min(self.backoff_ms * (self.multiplier ** attempt),
                   self.max_backoff_ms) / 1000.0
        r = rng.random() if rng is not None else random.random()
        return base * (1.0 - self.jitter * r)


def call_with_retries(fn: Callable[[], T], *,
                      policy: Optional[RetryPolicy] = None,
                      retry_on: Tuple[Type[BaseException], ...] = (
                          ConnectionError,),
                      no_retry: Tuple[Type[BaseException], ...] = (),
                      describe: str = "",
                      sleep: Callable[[float], None] = time.sleep,
                      rng: Optional[random.Random] = None) -> T:
    """Run ``fn`` under ``policy``, retrying only ``retry_on`` failures.

    ``no_retry`` wins over ``retry_on`` (e.g. an auth error that happens
    to subclass a retryable type).  ``sleep`` and ``rng`` are injectable
    so tests stay instant and deterministic.
    """
    if policy is None:
        policy = RetryPolicy.from_env()
    attempt = 0
    slept = 0.0
    while True:
        try:
            return fn()
        except no_retry:
            raise
        except retry_on as e:
            if attempt >= policy.retries:
                raise
            delay = policy.delay_s(attempt, rng)
            if policy.budget_s is not None \
                    and slept + delay > policy.budget_s:
                # The next backoff would blow the per-call stall budget:
                # fail NOW with the underlying error so bulk callers
                # (chunked KV streams) see a bounded worst case.
                raise
            slept += delay
            logger.debug("retry %d/%d for %s after %s: %.3fs backoff",
                         attempt + 1, policy.retries, describe or "call",
                         e, delay)
            try:
                from ..timeline import metrics as _metrics
                _metrics.registry().counter(
                    "horovod_kv_retries_total",
                    "Control-plane requests retried after a transport "
                    "failure").inc()
            except Exception:
                pass
            sleep(delay)
            attempt += 1
