"""Prometheus ``/metrics`` endpoint on the ``http_kv`` server machinery.

Serves the process-wide :mod:`horovod_tpu.timeline.metrics` registry as
text exposition format 0.0.4 (plus ``/metrics.json`` for the snapshot
dict and ``/healthz`` for liveness probes).  Started by ``hvd.init()``
when ``HOROVOD_METRICS_PORT`` is set (>= 0; 0 binds an ephemeral port --
read it back from ``global_state().metrics_server.port``).

Auth is HMAC-*optional*, unlike :class:`~horovod_tpu.run.http_kv.
RendezvousServer` where it is mandatory: the endpoint is read-only
aggregate telemetry, and Prometheus scrapers cannot sign requests.  Pass
``secret_key=`` to require the same ``X-Hvd-Sig``/``X-Hvd-Ts`` scheme as
the KV plane when the port is exposed beyond loopback.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .http_kv import MAX_SKEW_S, SIG_HEADER, TS_HEADER, _signable
from .secret import check_digest


class MetricsServer:
    """Threaded read-only HTTP server over the metrics registry."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 secret_key: Optional[str] = None):
        secret = secret_key

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _verify(self) -> bool:
                if secret is None:
                    return True
                import time
                sig = self.headers.get(SIG_HEADER, "")
                ts = self.headers.get(TS_HEADER, "")
                try:
                    skew = abs(time.time() - float(ts))
                except ValueError:
                    return False
                if skew > MAX_SKEW_S:
                    return False
                return check_digest(
                    secret, _signable(self.command, self.path, ts, b""),
                    sig)

            def _reply(self, code: int, body: bytes = b"",
                       ctype: str = "text/plain") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/healthz":
                    # Liveness must answer WITHOUT a signature even when
                    # HMAC auth is armed: kubelet/LB probes cannot sign,
                    # and the reply ("ok") carries no telemetry.  The
                    # data endpoints below stay protected.
                    return self._reply(200, b"ok\n")
                if not self._verify():
                    return self._reply(403)
                from ..timeline import metrics as _metrics
                try:
                    if path in ("/", "/metrics"):
                        return self._reply(
                            200, _metrics.render_prometheus().encode(),
                            _metrics.CONTENT_TYPE)
                    if path == "/metrics.json":
                        body = json.dumps(
                            _metrics.metrics_snapshot()).encode()
                        return self._reply(200, body, "application/json")
                except Exception as e:  # a bad collector must not 404
                    return self._reply(
                        500, f"metrics render failed: {e}\n".encode())
                self._reply(404)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="hvd-tpu-metrics")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
