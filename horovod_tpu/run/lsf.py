"""LSF cluster detection for the launcher.

Parity with ``horovod/runner/util/lsf.py`` (LSF environment probing) and
the spirit of ``horovod/runner/js_run.py``: when ``hvdrun`` starts inside
an LSF job with no explicit ``-H``/``--hostfile``, the host list is
derived from the scheduler's environment —

- ``LSB_DJOB_RANKFILE``: one hostname per allocated slot (repeats mean
  multiple slots on that host); preferred when present because it
  reflects the actual rank layout ``jsrun``/``blaunch`` would use.
- ``LSB_MCPU_HOSTS``: ``"host1 n1 host2 n2 ..."`` alternating host /
  core-count pairs.

The reference execs ``jsrun`` to fan out; this launcher instead spawns
local controller processes, so on a multi-host LSF allocation each worker
VM runs ``hvdrun`` with its local slots and a shared ``--coordinator``
(see ``launch.py``).  The parsing surface is what carries over.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import List, Tuple


def using_lsf() -> bool:
    """True when running inside an LSF job (``LSB_JOBID`` set)."""
    return "LSB_JOBID" in os.environ


def get_compute_hosts() -> List[Tuple[str, int]]:
    """``(host, slots)`` list from the LSF environment.

    Slot counts come from the scheduler itself (rank-file line repeats /
    MCPU core counts).  Raises ``ValueError`` if no usable LSF host
    information is found or the format is malformed.
    """
    rankfile = os.environ.get("LSB_DJOB_RANKFILE")
    if rankfile and os.path.exists(rankfile):
        with open(rankfile) as f:
            hosts = [h for h in (raw.strip() for raw in f) if h]
        # On CSM/jsrun systems the first line is the slotless batch/launch
        # node; on plain LSF (bsub -n N) every line is a compute slot.
        # Drop the first line when it is clearly the launch node: it never
        # recurs AND (it matches LSB_SUB_HOST, or later hosts hold multiple
        # slots while it holds one -- the CSM signature).  A one-slot-per-
        # host allocation (span[ptile=1]) has no recurring hosts at all, so
        # nothing is dropped there.  The residual ambiguity (a slotless
        # launch node heading an otherwise ptile=1 rankfile) is
        # undecidable from the file alone; pass -H explicitly in that case.
        rest = hosts[1:]
        sub_host = os.environ.get("LSB_SUB_HOST")

        def _stem(h):  # FQDN vs short-name tolerant compare
            return h.split(".", 1)[0].lower()

        # The slot-shape fallback only applies when LSB_SUB_HOST is absent
        # or matches (by hostname stem): when it IS set and names a
        # different machine, hosts[0] is a genuine compute host (e.g. an
        # uneven plain-LSF spread from a login node), not the launch node.
        sub_matches = sub_host is None or _stem(hosts[0]) == _stem(sub_host)
        first_is_launch = (
            len(hosts) > 1 and hosts[0] not in rest and sub_matches
            and (sub_host is not None
                 or any(rest.count(h) > 1 for h in set(rest))))
        if first_is_launch:
            hosts = rest
        counts: "OrderedDict[str, int]" = OrderedDict()
        for host in hosts:
            counts[host] = counts.get(host, 0) + 1
        if counts:
            return list(counts.items())

    # Non-CSM fallback: every LSB_MCPU_HOSTS entry carries an allocated
    # core count, so all entries (including the submission host's) are
    # genuine compute slots; jsrun-style systems with a slotless batch
    # node provide the rankfile above, which is preferred.
    mcpu = os.environ.get("LSB_MCPU_HOSTS", "").split()
    if mcpu:
        if len(mcpu) % 2:
            raise ValueError(
                f"malformed LSB_MCPU_HOSTS (odd token count): {mcpu!r}")
        out: "OrderedDict[str, int]" = OrderedDict()
        for host, n in zip(mcpu[::2], mcpu[1::2]):
            try:
                slots = int(n)
            except ValueError:
                raise ValueError(
                    f"malformed LSB_MCPU_HOSTS slot count {n!r}")
            if slots > 0:
                out[host] = out.get(host, 0) + slots
        if out:
            return list(out.items())

    raise ValueError("LSF job detected (LSB_JOBID set) but neither "
                     "LSB_DJOB_RANKFILE nor LSB_MCPU_HOSTS is usable")
