"""Launcher (horovodrun analogue): see horovod_tpu/run/launch.py."""

from .launch import run_command, worker_env, check_build, free_port  # noqa: F401
