"""Launcher (horovodrun analogue): see horovod_tpu/run/launch.py."""

from .launch import run_command, worker_env, check_build, free_port  # noqa: F401


def run(func, args=(), kwargs=None, np=1, cpu=False, slots=1,
        use_ray=False, verbose=0):
    # verbose threads into worker logging below (HOROVOD_LOG_LEVEL).
    """Programmatic launcher (reference ``horovod.run.run()`` API).

    Runs ``func(*args, **kwargs)`` on ``np`` worker processes with the
    framework env wired (coordinator, ranks); returns the rank-ordered
    results.  ``cpu=True`` forces the XLA:CPU backend per worker (the
    local test mesh); on a TPU pod each worker VM's agent calls this with
    its local slot count instead.
    """
    import os

    from ..ray import RayExecutor

    if verbose:
        os.environ.setdefault("HOROVOD_LOG_LEVEL",
                              "debug" if verbose > 1 else "info")
    ex = RayExecutor(num_workers=np, cpu=cpu, use_ray=use_ray,
                     slots_per_worker=slots)
    ex.start()
    try:
        return ex.run(func, args=args, kwargs=kwargs or {})
    finally:
        ex.shutdown()
