"""Launcher (horovodrun analogue): see horovod_tpu/run/launch.py."""

from .launch import run_command, worker_env, check_build, free_port  # noqa: F401


def run(func, args=(), kwargs=None, np=1, cpu=False, slots=1,
        use_ray=False, verbose=0):
    """Programmatic launcher (reference ``horovod.run.run()`` API).

    Runs ``func(*args, **kwargs)`` on ``np`` worker processes with the
    framework env wired (coordinator, ranks); returns the rank-ordered
    results.  ``cpu=True`` forces the XLA:CPU backend per worker (the
    local test mesh); on a TPU pod each worker VM's agent calls this with
    its local slot count instead.
    """
    import os

    from ..ray import RayExecutor

    # verbose reaches workers through their env dict (works for both the
    # local-process and ray-actor backends; no process-global mutation).
    # An explicit user HOROVOD_LOG_LEVEL wins over the verbose default.
    extra = {}
    if verbose and "HOROVOD_LOG_LEVEL" not in os.environ:
        extra = {"HOROVOD_LOG_LEVEL": "debug" if verbose > 1 else "info"}
    ex = RayExecutor(num_workers=np, cpu=cpu, use_ray=use_ray,
                     slots_per_worker=slots, extra_env=extra)
    ex.start()
    try:
        return ex.run(func, args=args, kwargs=kwargs or {})
    finally:
        ex.shutdown()
