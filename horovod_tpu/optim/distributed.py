"""DistributedOptimizer: gradient allreduce fused into the update.

JAX-native analogue of ``horovod/torch/optimizer.py::DistributedOptimizer``
(grad-hook allreduce + ``synchronize()`` before ``step()``) and
``horovod/tensorflow/__init__.py::DistributedGradientTape``.  Because the
whole step is traced, the "hook + background negotiation + synchronize"
machinery collapses into a pure function: gradients are bucketed through
the fusion planner, one ``psum`` per bucket is emitted inside the step, and
XLA overlaps those collectives with the backward pass automatically (the
latency-hiding the reference needs its async enqueue machinery for).

Supports the reference's knobs: reduce op (Average/Sum/Adasum), fp16/bf16
compression, process sets, prescale/postscale,
``backward_passes_per_step`` (local gradient accumulation: N-1 steps
accumulate locally, the Nth allreduces the running sum -- same traffic
saving as the reference's ``backward_passes_per_step``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from ..collectives import ops as _ops
from ..collectives.compression import Compression
from ..collectives.reduce_op import ReduceOp, Average
from ..controller.fusion import fused_tree_collective


def allreduce_gradients(grads,
                        op: ReduceOp = Average,
                        *,
                        compression=Compression.none,
                        fusion_threshold: Optional[int] = None,
                        axes=None,
                        process_set=None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0):
    """Fused in-step allreduce of a gradient pytree (the hot path).

    Two further knobs resolve at TRACE time (the reference's
    ParameterManager tunes both; ours does too under
    ``HOROVOD_AUTOTUNE=1``): the hierarchical-allreduce algorithm choice
    on (dcn, ici) meshes (``HOROVOD_HIERARCHICAL_ALLREDUCE`` /
    autotuned) and -- opt-in, it changes wire numerics
    (``HOROVOD_AUTOTUNE_COMPRESSION=1``) -- the compression codec.
    """
    from ..collectives.compression import is_fp8
    from ..controller.fusion import exchange_chunk_bytes
    from ..core.state import global_state
    st = global_state()
    chunk_bytes = exchange_chunk_bytes()
    tuner = st.autotuner
    if tuner is not None:
        override = tuner.compression_override(compression)
        if (is_fp8(override) and not is_fp8(compression)
                and process_set is not None):
            # The tuner's fp8 axis cannot serve subset reductions (the
            # quantized exchange has no masked identity); keep the
            # configured codec for this sample instead of failing it.
            override = compression
        compression = override
        explicit_hier = tuner.hierarchical_explicit()
    else:
        explicit_hier = bool(st.config and st.config.hierarchical_allreduce)

    def resolved_axes():
        if axes is not None:
            return tuple((axes,) if isinstance(axes, str) else axes)
        return tuple(st.mesh.axis_names) if st.mesh is not None else ()

    def collective(buf):
        ax = resolved_axes()
        if is_fp8(compression):
            # Exchange-level codec: the collective itself changes (a psum
            # cannot carry fp8 -- compression.py module docstring).
            from ..collectives.reduce_op import Adasum
            if op is Adasum:
                return _ops.allreduce(
                    buf, op, axes=axes, process_set=process_set,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor, wire_codec="fp8")
            if process_set is not None:
                raise NotImplementedError(
                    "Compression.fp8 does not support process-set "
                    "Sum/Average reductions (no masked identity for a "
                    "quantized exchange); use fp16/bf16 there")
            return _ops.fp8_allreduce(
                buf, op, axes=axes, prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        c, ctx = compression.compress(buf)
        if (explicit_hier and process_set is None and len(ax) == 2
                and op in (_ops.Sum, Average)):
            r = _ops.hierarchical_allreduce(
                c, op, dcn_axis=ax[0], ici_axis=ax[1],
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        elif (chunk_bytes > 0 and process_set is None
              and op in (_ops.Sum, Average)):
            # HOROVOD_EXCHANGE_CHUNK_MB (or the tuner's chunk axis):
            # decompose the bucket into overlap-friendly RS+AG chunks.
            # Chunking acts on the compressed wire buffer, so it composes
            # with fp16/bf16 codecs.
            r = _ops.chunked_allreduce(
                c, op, chunk_bytes=chunk_bytes, axes=ax,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        else:
            r = _ops.allreduce(c, op, axes=axes, process_set=process_set,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor)
        return compression.decompress(r, ctx)

    # Axis sizes are static at trace time: a one-device reduction is the
    # identity (every reduce op over a single member returns its input), so
    # skip the pack/unpack copies and apply the collective leaf-wise -- XLA
    # deletes the size-1 psum and fuses the scale/compression casts into
    # the surrounding update.  The reference pays its fusion-buffer memcpys
    # even at np=1; knowing the world size at trace time is exactly what
    # lets the TPU build not to.
    try:
        world = _ops.axis_size(axes)
    except Exception:  # outside a traced mesh context: keep the fused path
        world = None
    if world == 1:
        return jax.tree.map(collective, grads)

    return fused_tree_collective(grads, collective, fusion_threshold)


class _AccumState(NamedTuple):
    counter: jnp.ndarray          # int32 scalar
    accum: Any                    # gradient-shaped pytree
    inner: Any                    # wrapped optimizer state


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         *,
                         op: ReduceOp = Average,
                         compression=Compression.none,
                         fusion_threshold: Optional[int] = None,
                         axes=None,
                         process_set=None,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0,
                         backward_passes_per_step: int = 1
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates see globally-reduced gradients.

    Use inside a step traced over the mesh (``shard_map`` or the
    :func:`horovod_tpu.training.train_step` helper)::

        opt = hvd.DistributedOptimizer(optax.adamw(1e-3),
                                       compression=hvd.Compression.bf16)
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    def _reduce(grads):
        return allreduce_gradients(
            grads, op, compression=compression,
            fusion_threshold=fusion_threshold, axes=axes,
            process_set=process_set, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)

    if backward_passes_per_step == 1:
        def init(params):
            return optimizer.init(params)

        def update(grads, state, params=None, **extra):
            return optimizer.update(_reduce(grads), state, params, **extra)

        # zero_stage=1 replaces this allreduce with a reduce-scatter; the
        # zero path detects the wrap through this marker and rejects it.
        update._hvd_allreduce = True
        # The microbatched step (training.py, microbatches=k>1) unwraps the
        # optimizer and runs the exchange itself (per-microbatch shard
        # reduce-scatter + one allgather), so it needs the inner optimizer
        # and the exchange parameters this wrap would have applied.  Only
        # the plain (non-accumulating) wrap exposes them: combining k>1
        # with backward_passes_per_step>1 is rejected at build time.
        update._hvd_inner = optimizer
        update._hvd_exchange = dict(
            op=op, compression=compression, fusion_threshold=fusion_threshold,
            axes=axes, process_set=process_set,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
        return optax.GradientTransformation(init, update)

    n = backward_passes_per_step

    def init(params):
        return _AccumState(
            counter=jnp.zeros((), jnp.int32),
            accum=jax.tree.map(jnp.zeros_like, params),
            inner=optimizer.init(params))

    def update(grads, state, params=None, **extra):
        accum = jax.tree.map(lambda a, g: a + g, state.accum, grads)
        is_sync = state.counter == n - 1

        def do_sync(_):
            mean_grads = jax.tree.map(lambda a: a / n, accum)
            reduced = _reduce(mean_grads)
            updates, inner = optimizer.update(reduced, state.inner, params,
                                              **extra)
            zeroed = jax.tree.map(jnp.zeros_like, accum)
            return updates, _AccumState(jnp.zeros((), jnp.int32), zeroed,
                                        inner)

        def skip(_):
            updates = jax.tree.map(jnp.zeros_like, grads)
            return updates, _AccumState(state.counter + 1, accum, state.inner)

        return jax.lax.cond(is_sync, do_sync, skip, None)

    update._hvd_allreduce = True
    return optax.GradientTransformation(init, update)


def DistributedAdasumOptimizer(optimizer: optax.GradientTransformation,
                               **kwargs) -> optax.GradientTransformation:
    """Adasum variant (``_DistributedAdasumOptimizer`` parity)."""
    from ..collectives.reduce_op import Adasum
    kwargs["op"] = Adasum
    return DistributedOptimizer(optimizer, **kwargs)
