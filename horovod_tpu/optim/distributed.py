"""DistributedOptimizer: gradient allreduce fused into the update.

JAX-native analogue of ``horovod/torch/optimizer.py::DistributedOptimizer``
(grad-hook allreduce + ``synchronize()`` before ``step()``) and
``horovod/tensorflow/__init__.py::DistributedGradientTape``.  Because the
whole step is traced, the "hook + background negotiation + synchronize"
machinery collapses into a pure function: gradients are bucketed through
the fusion planner, one ``psum`` per bucket is emitted inside the step, and
XLA overlaps those collectives with the backward pass automatically (the
latency-hiding the reference needs its async enqueue machinery for).

Supports the reference's knobs: reduce op (Average/Sum/Adasum), fp16/bf16
compression, process sets, prescale/postscale,
``backward_passes_per_step`` (local gradient accumulation: N-1 steps
accumulate locally, the Nth allreduces the running sum -- same traffic
saving as the reference's ``backward_passes_per_step``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from ..collectives import ops as _ops
from ..collectives.compression import (Compression, is_error_feedback,
                                       is_hier_legs, is_powersgd,
                                       parse_compression,
                                       wire_payload_bytes)
from ..collectives.reduce_op import ReduceOp, Average
from ..controller.fusion import fused_tree_collective


def _resolve_compression(compression):
    """``None`` defers to ``HOROVOD_COMPRESSION`` (a spec string resolved
    through :func:`parse_compression`); an explicit codec or spec string is
    taken as-is.  Passing ``Compression.none`` explicitly disables the env
    default."""
    if compression is None:
        from ..core.state import global_state
        cfg = global_state().config
        spec = cfg.compression if cfg is not None else None
        return parse_compression(spec)
    return parse_compression(compression)


def _ef_enabled() -> bool:
    """``HOROVOD_EF_RESIDUAL`` (default on): whether the EF codecs carry
    residual state across steps.  Off means the compression error is
    dropped every step -- useful only for ablations."""
    from ..core.state import global_state
    cfg = global_state().config
    return cfg.ef_residual if cfg is not None else True


def _hier_axes(axes):
    """Resolve ``axes`` to the two-level ``(dcn, ici)`` pair, or ``None``
    when the effective mesh is flat (single axis)."""
    from ..core.state import global_state
    if axes is None:
        mesh = global_state().mesh
        ax = tuple(mesh.axis_names) if mesh is not None else ()
    else:
        ax = tuple((axes,) if isinstance(axes, str) else axes)
    return ax if len(ax) == 2 else None


def _stateless_ef_collective(buf, compression, op, axes,
                             prescale_factor, postscale_factor):
    """One EF-codec exchange with no residual (autotune sampling, direct
    ``allreduce_gradients`` calls, the eager path).  Non-floating buckets
    fall back to the plain allreduce -- the codecs are float-only."""
    if not jnp.issubdtype(buf.dtype, jnp.floating):
        return _ops.allreduce(buf, op, axes=axes,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor)
    if is_hier_legs(compression):
        pair = _hier_axes(axes)
        if pair is None:
            # Flat mesh: the DCN hop degenerates; run the EF codec over
            # the whole (single-axis) world instead.
            compression = compression.dcn
        else:
            out, _ = _ops.hierarchical_allreduce(
                buf, op, dcn_axis=pair[0], ici_axis=pair[1],
                dcn_codec=compression.dcn, ici_codec=compression.ici,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
            return out
    if is_powersgd(compression):
        out, _ = _ops.powersgd_allreduce(
            buf, op, rank=compression.rank, axes=axes,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    else:
        out, _ = _ops.topk_allreduce(
            buf, op, fraction=compression.fraction, axes=axes,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    return out


def allreduce_gradients(grads,
                        op: ReduceOp = Average,
                        *,
                        compression=Compression.none,
                        fusion_threshold: Optional[int] = None,
                        axes=None,
                        process_set=None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0):
    """Fused in-step allreduce of a gradient pytree (the hot path).

    Two further knobs resolve at TRACE time (the reference's
    ParameterManager tunes both; ours does too under
    ``HOROVOD_AUTOTUNE=1``): the hierarchical-allreduce algorithm choice
    on (dcn, ici) meshes (``HOROVOD_HIERARCHICAL_ALLREDUCE`` /
    autotuned) and -- opt-in, it changes wire numerics
    (``HOROVOD_AUTOTUNE_COMPRESSION=1``) -- the compression codec.
    """
    from ..collectives.compression import is_fp8
    from ..collectives.reduce_op import Adasum as _Adasum
    from ..controller.fusion import exchange_chunk_bytes
    from ..core.state import global_state
    compression = parse_compression(compression)
    st = global_state()
    chunk_bytes = exchange_chunk_bytes()
    tuner = st.autotuner
    if tuner is not None:
        override = tuner.compression_override(compression)
        if (is_fp8(override) and not is_fp8(compression)
                and process_set is not None):
            # The tuner's fp8 axis cannot serve subset reductions (the
            # quantized exchange has no masked identity); keep the
            # configured codec for this sample instead of failing it.
            override = compression
        if (is_error_feedback(override)
                and not is_error_feedback(compression)
                and (process_set is not None or op is _Adasum)):
            # Same escape hatch for the tuner's EF-codec axis: the factored/
            # sparse exchanges serve full-mesh Sum/Average only.
            override = compression
        compression = override
        explicit_hier = tuner.hierarchical_explicit()
    else:
        explicit_hier = bool(st.config and st.config.hierarchical_allreduce)
        if not explicit_hier and st.config is not None \
                and st.config.hierarchical:
            # HOROVOD_HIERARCHICAL topology spec implies the two-level
            # exchange (not just the two-level mesh).
            from ..parallel.mesh import parse_topology_spec
            try:
                explicit_hier = parse_topology_spec(st.config.hierarchical)[0]
            except ValueError:
                pass

    def resolved_axes():
        if axes is not None:
            return tuple((axes,) if isinstance(axes, str) else axes)
        return tuple(st.mesh.axis_names) if st.mesh is not None else ()

    def collective(buf):
        ax = resolved_axes()
        if is_error_feedback(compression):
            # Exchange-level EF codec WITHOUT residual state: the stateful
            # path lives in the DistributedOptimizer wrap (it owns the
            # residual carry); this surface serves tuner samples and
            # direct calls, where dropping the error is acceptable.
            if process_set is not None:
                raise NotImplementedError(
                    "powersgd/topk do not support process-set reductions "
                    "(no masked identity for a factored/sparse exchange); "
                    "use fp16/bf16 there")
            return _stateless_ef_collective(
                buf, compression, op, axes, prescale_factor,
                postscale_factor)
        if is_fp8(compression):
            # Exchange-level codec: the collective itself changes (a psum
            # cannot carry fp8 -- compression.py module docstring).
            from ..collectives.reduce_op import Adasum
            if op is Adasum:
                return _ops.allreduce(
                    buf, op, axes=axes, process_set=process_set,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor, wire_codec="fp8")
            if process_set is not None:
                raise NotImplementedError(
                    "Compression.fp8 does not support process-set "
                    "Sum/Average reductions (no masked identity for a "
                    "quantized exchange); use fp16/bf16 there")
            return _ops.fp8_allreduce(
                buf, op, axes=axes, prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        c, ctx = compression.compress(buf)
        hier_ok = (process_set is None and len(ax) == 2
                   and op in (_ops.Sum, Average))
        if is_hier_legs(compression):
            # Per-leg codec (ici:...,dcn:...): the exchange itself is the
            # two-level decomposition with each hop's codec applied on
            # that hop only.  On a flat mesh the DCN hop degenerates, so
            # ride the psum-compatible ICI codec on the flat exchange.
            if hier_ok:
                r = _ops.hierarchical_allreduce(
                    c, op, dcn_axis=ax[0], ici_axis=ax[1],
                    dcn_codec=compression.dcn, ici_codec=compression.ici,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor)
                return r
            _note_flat_leg(c, compression.ici)
            ci, ictx = compression.ici.compress(c)
            r = _ops.allreduce(ci, op, axes=axes, process_set=process_set,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor)
            return compression.ici.decompress(r, ictx)
        if explicit_hier and hier_ok:
            r = _ops.hierarchical_allreduce(
                c, op, dcn_axis=ax[0], ici_axis=ax[1],
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        elif (chunk_bytes > 0 and process_set is None
              and op in (_ops.Sum, Average)):
            # HOROVOD_EXCHANGE_CHUNK_MB (or the tuner's chunk axis):
            # decompose the bucket into overlap-friendly RS+AG chunks.
            # Chunking acts on the compressed wire buffer, so it composes
            # with fp16/bf16 codecs.
            r = _ops.chunked_allreduce(
                c, op, chunk_bytes=chunk_bytes, axes=ax,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        else:
            _note_flat_leg(buf, compression)
            r = _ops.allreduce(c, op, axes=axes, process_set=process_set,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor)
        return compression.decompress(r, ctx)

    def _note_flat_leg(buf, comp):
        # Flat fused-bucket exchange: register the plan-IR row at trace
        # time (the hier/chunked/fp8/EF paths note inside their ops; a
        # world-1 "reduction" is the identity and moves no bytes).
        if world == 1:
            return
        from ..controller import fusion as _fusion
        from ..timeline import spans as _spans
        _spans.note_leg(_fusion.plan_exchange(
            "flat", size=int(buf.size), dtype=str(buf.dtype),
            compression=comp).legs[0])

    # Axis sizes are static at trace time: a one-device reduction is the
    # identity (every reduce op over a single member returns its input), so
    # skip the pack/unpack copies and apply the collective leaf-wise -- XLA
    # deletes the size-1 psum and fuses the scale/compression casts into
    # the surrounding update.  The reference pays its fusion-buffer memcpys
    # even at np=1; knowing the world size at trace time is exactly what
    # lets the TPU build not to.
    try:
        world = _ops.axis_size(axes)
    except Exception:  # outside a traced mesh context: keep the fused path
        world = None
    if world == 1:
        return jax.tree.map(collective, grads)

    # The codec name rides the plan memo key: an EF-codec plan pins the
    # residual-state shapes, so it must never alias a plain plan of the
    # same leaf list at the same threshold.
    return fused_tree_collective(grads, collective, fusion_threshold,
                                 extra=(compression.__name__,))


class _AccumState(NamedTuple):
    counter: jnp.ndarray          # int32 scalar
    accum: Any                    # gradient-shaped pytree
    inner: Any                    # wrapped optimizer state


class _EFState(NamedTuple):
    """Optimizer-state carry for the error-feedback codecs.

    ``residuals`` is one flat f32 array PER FUSION BUCKET with a leading
    world axis (``[world, bucket_size]`` globally, ``[1, bucket_size]``
    inside the shard-mapped step) -- residuals are PER-RANK state (each
    rank's compression error differs), so ``make_train_step`` shards them
    ``P(axes)`` like ZeRO state while ``inner`` stays replicated.
    """
    residuals: Any                # tuple of [world, bucket_size] f32
    inner: Any                    # wrapped optimizer state


def _ef_threshold(fusion_threshold: Optional[int]) -> int:
    """Bucket threshold for EF plans, resolved ONCE and pinned: residual
    shapes live in the optimizer state, so the autotuner's threshold axis
    must not re-plan under them (config value, never the tuner's)."""
    if fusion_threshold is not None:
        return int(fusion_threshold)
    from ..core.state import global_state
    cfg = global_state().config
    return cfg.fusion_threshold if cfg is not None else 64 * 1024 * 1024


def _ef_world() -> int:
    """Leading residual axis: the FULL mesh size (``make_train_step``
    shards optimizer state over every mesh axis)."""
    from ..core.state import global_state
    mesh = global_state().mesh
    return int(mesh.devices.size) if mesh is not None else 1


def ef_bucket_plan(leaves, fusion_threshold: Optional[int], compression):
    from ..controller.fusion import plan_buckets
    return plan_buckets(leaves, _ef_threshold(fusion_threshold),
                        extra=("ef", compression.__name__))


def ef_residual_shape(size: int, compression) -> tuple:
    """Per-bucket residual row shape (no leading world axis).

    Flat EF codecs carry ``(size,)`` -- the whole bucket's unsent error.
    Per-leg codecs carry ``(2, shard)`` -- one row per leg of the
    two-level exchange, where ``shard`` is the DCN hop's operand width
    (``padded / n_ici``).  The ICI legs are exact reduce-scatter /
    allgather, so leg 0 stays identically zero; leg 1 holds the DCN
    codec's unsent residual.  The leg axis keeps the state
    self-describing for join replay and elastic resize.
    """
    if is_hier_legs(compression):
        from ..core.state import global_state
        mesh = global_state().mesh
        names = tuple(mesh.axis_names) if mesh is not None else ()
        n_ici = int(mesh.shape[names[-1]]) if len(names) == 2 else 1
        quantum = _ops.microbatch_pad_quantum(n_ici)
        padded = size + (-size) % quantum
        return (2, padded // n_ici)
    return (int(size),)


def ef_init_residuals(params, fusion_threshold: Optional[int], compression):
    """Zero residual carry matching the EF bucket plan of ``params``-shaped
    gradients: one ``[world, *ef_residual_shape]`` f32 array per bucket."""
    leaves = jax.tree.leaves(params)
    spec = ef_bucket_plan(leaves, fusion_threshold, compression)
    world = _ef_world()
    return tuple(
        jnp.zeros((world,) + ef_residual_shape(
            sum(s.size for s in lspecs), compression), jnp.float32)
        for _dt, lspecs in spec.buffers)


def _note_compression_ratio(spec, compression) -> None:
    """Host-side ``compression_ratio`` accounting (trace-time: the ratio
    is a pure function of the static bucket shapes).  Feeds the timeline
    counter track when one is active AND the metrics-registry gauges
    unconditionally -- the gauges are set (not incremented) because this
    fires once per trace, not per step; per-step totals come from the
    StepReport instrumentation."""
    from ..controller.fusion import hier_mesh_shape, plan_hier_legs
    from ..core.state import global_state
    hier_shape = hier_mesh_shape() if is_hier_legs(compression) else None
    raw = wire = 0
    for dt, lspecs in spec.buffers:
        size = sum(s.size for s in lspecs)
        itemsize = jnp.dtype(dt).itemsize
        raw += size * itemsize
        if hier_shape is not None:
            wire += sum(l.nbytes for l in plan_hier_legs(
                size, dt, n_dcn=hier_shape[0], n_ici=hier_shape[1],
                compression=compression))
        else:
            wire += wire_payload_bytes(compression, size, itemsize)
    if wire <= 0:
        return
    tl = global_state().timeline
    if tl is not None:
        tl.counters({"compression_ratio": raw / wire,
                     "wire_bytes_per_step": wire,
                     "uncompressed_bytes_per_step": raw})
    from ..timeline import metrics as _metrics
    reg = _metrics.registry()
    reg.gauge("horovod_compression_ratio",
              "uncompressed / wire bytes of the gradient exchange"
              ).set(raw / wire)
    reg.gauge("horovod_wire_bytes_per_step",
              "Per-chip exchange wire bytes per optimizer step").set(wire)
    reg.gauge("horovod_uncompressed_bytes_per_step",
              "Equivalent uncompressed exchange bytes per optimizer step"
              ).set(raw)


def ef_exchange(grads, residuals, *, compression, op=Average,
                fusion_threshold: Optional[int] = None, axes=None,
                prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Error-feedback fused gradient exchange: the stateful hot path.

    ``residuals`` is the per-bucket tuple of flat f32 arrays from the
    PREVIOUS step (local view, no leading world axis).  Returns
    ``(reduced_grads, new_residuals)``.  With ``HOROVOD_EF_RESIDUAL=0``
    the residual input is ignored (zeros) and the carry is returned
    unchanged, so the state shape stays stable across the flag.
    """
    from ..controller.fusion import pack, unpack
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return grads, residuals
    spec = ef_bucket_plan(leaves, fusion_threshold, compression)
    if len(residuals) != len(spec.buffers):
        raise ValueError(
            f"EF residual carry has {len(residuals)} buckets but the plan "
            f"has {len(spec.buffers)} -- optimizer state initialized under "
            f"a different fusion threshold or codec?")
    buffers = pack(leaves, spec)
    feed = _ef_enabled()
    # Trace-time leg registration for the straggler report (fires once
    # per trace, exactly like _note_compression_ratio below).
    from ..timeline import spans as _spans
    hier = is_hier_legs(compression)
    hier_pair = _hier_axes(axes) if hier else None
    if hier and hier_pair is None:
        raise NotImplementedError(
            "per-leg error-feedback compression (ici:...,dcn:powersgd/topk)"
            " needs the two-level (dcn, ici) mesh; set HOROVOD_HIERARCHICAL"
            " or use the flat codec spec instead")
    out_bufs, new_res = [], []
    for i, (buf, res, (dt, _ls)) in enumerate(
            zip(buffers, residuals, spec.buffers)):
        if not hier:
            # The two-level path notes its own hier/* legs per hop.  The
            # ledger row (wire payload accounting) comes from the shared
            # exchange-plan IR; the nested powersgd/topk collective rows
            # fire from inside the op itself.
            from ..controller import fusion as _fusion
            _spans.note_leg(
                _fusion.plan_exchange(
                    "ef", size=int(buf.size), dtype=str(buf.dtype),
                    compression=compression).legs[0],
                bucket_id=i)
        if not jnp.issubdtype(buf.dtype, jnp.floating):
            out_bufs.append(_ops.allreduce(
                buf, op, axes=axes, prescale_factor=prescale_factor,
                postscale_factor=postscale_factor))
            new_res.append(res)
            continue
        if hier:
            # Residual row is [2, shard]: leg 0 (ICI) is exact and stays
            # zero, leg 1 carries the DCN hop's unsent error.
            r_in = res[1] if feed else None
            out, r_out = _ops.hierarchical_allreduce(
                buf, op, dcn_axis=hier_pair[0], ici_axis=hier_pair[1],
                dcn_codec=compression.dcn, ici_codec=compression.ici,
                dcn_residual=r_in,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
            out_bufs.append(out)
            new_res.append(jnp.stack([jnp.zeros_like(r_out), r_out])
                           if feed else res)
            continue
        r_in = res if feed else None
        if is_powersgd(compression):
            out, r_out = _ops.powersgd_allreduce(
                buf, op, rank=compression.rank, axes=axes, residual=r_in,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        else:
            out, r_out = _ops.topk_allreduce(
                buf, op, fraction=compression.fraction, axes=axes,
                residual=r_in, prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        out_bufs.append(out)
        new_res.append(r_out if feed else res)
    _note_compression_ratio(spec, compression)
    return (jax.tree.unflatten(treedef, unpack(out_bufs, spec)),
            tuple(new_res))


def is_ef_optimizer(optimizer) -> bool:
    """True when ``optimizer`` is a DistributedOptimizer wrap whose codec
    needs the error-feedback state carry (its state is an :class:`_EFState`
    and must be sharded ``P(axes)`` on the residual leaves)."""
    ex = getattr(optimizer.update, "_hvd_exchange", None)
    return ex is not None and is_error_feedback(ex["compression"])


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         *,
                         op: ReduceOp = Average,
                         compression=None,
                         fusion_threshold: Optional[int] = None,
                         axes=None,
                         process_set=None,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0,
                         backward_passes_per_step: int = 1
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates see globally-reduced gradients.

    Use inside a step traced over the mesh (``shard_map`` or the
    :func:`horovod_tpu.training.train_step` helper)::

        opt = hvd.DistributedOptimizer(optax.adamw(1e-3),
                                       compression=hvd.Compression.bf16)

    ``compression`` accepts a codec class, a spec string
    (``"powersgd:2"``, ``"topk:0.01"``, ``"bf16"``, ...), or ``None`` to
    follow ``HOROVOD_COMPRESSION``.  The error-feedback codecs
    (``Compression.powersgd(r)`` / ``Compression.topk(f)``) make the
    optimizer STATEFUL beyond the inner state: ``init`` returns an
    :class:`_EFState` carrying one per-rank residual array per fusion
    bucket, and each ``update`` runs the factored/sparse exchange with the
    residual fed back (``HOROVOD_EF_RESIDUAL``).
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    compression = _resolve_compression(compression)

    if is_error_feedback(compression):
        if process_set is not None:
            raise NotImplementedError(
                "powersgd/topk do not support process-set reductions; use "
                "fp16/bf16 compression there")
        from ..collectives.reduce_op import Adasum as _Adasum
        if op is _Adasum:
            raise NotImplementedError(
                "powersgd/topk support Sum/Average reductions only")
        if backward_passes_per_step != 1:
            raise NotImplementedError(
                "error-feedback compression with backward_passes_per_step"
                " > 1 is not supported; use microbatches=k instead "
                "(residual applied once per optimizer step)")

        def ef_init(params):
            return _EFState(
                residuals=ef_init_residuals(params, fusion_threshold,
                                            compression),
                inner=optimizer.init(params))

        def ef_update(grads, state, params=None, **extra):
            if not isinstance(state, _EFState):
                # Checkpoint restore may rebuild the carry as a plain
                # 2-tuple; the layout is positional either way.
                state = _EFState(*state)
            local_res = tuple(r[0] for r in state.residuals)
            reduced, new_res = ef_exchange(
                grads, local_res, compression=compression, op=op,
                fusion_threshold=fusion_threshold, axes=axes,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
            updates, inner = optimizer.update(reduced, state.inner, params,
                                              **extra)
            return updates, _EFState(tuple(r[None] for r in new_res), inner)

        ef_update._hvd_allreduce = True
        ef_update._hvd_inner = optimizer
        ef_update._hvd_exchange = dict(
            op=op, compression=compression, fusion_threshold=fusion_threshold,
            axes=axes, process_set=process_set,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
        return optax.GradientTransformation(ef_init, ef_update)

    def _reduce(grads):
        return allreduce_gradients(
            grads, op, compression=compression,
            fusion_threshold=fusion_threshold, axes=axes,
            process_set=process_set, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)

    if backward_passes_per_step == 1:
        def init(params):
            return optimizer.init(params)

        def update(grads, state, params=None, **extra):
            return optimizer.update(_reduce(grads), state, params, **extra)

        # zero_stage=1 replaces this allreduce with a reduce-scatter; the
        # zero path detects the wrap through this marker and rejects it.
        update._hvd_allreduce = True
        # The microbatched step (training.py, microbatches=k>1) unwraps the
        # optimizer and runs the exchange itself (per-microbatch shard
        # reduce-scatter + one allgather), so it needs the inner optimizer
        # and the exchange parameters this wrap would have applied.  Only
        # the plain (non-accumulating) wrap exposes them: combining k>1
        # with backward_passes_per_step>1 is rejected at build time.
        update._hvd_inner = optimizer
        update._hvd_exchange = dict(
            op=op, compression=compression, fusion_threshold=fusion_threshold,
            axes=axes, process_set=process_set,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
        return optax.GradientTransformation(init, update)

    n = backward_passes_per_step

    def init(params):
        return _AccumState(
            counter=jnp.zeros((), jnp.int32),
            accum=jax.tree.map(jnp.zeros_like, params),
            inner=optimizer.init(params))

    def update(grads, state, params=None, **extra):
        accum = jax.tree.map(lambda a, g: a + g, state.accum, grads)
        is_sync = state.counter == n - 1

        def do_sync(_):
            mean_grads = jax.tree.map(lambda a: a / n, accum)
            reduced = _reduce(mean_grads)
            updates, inner = optimizer.update(reduced, state.inner, params,
                                              **extra)
            zeroed = jax.tree.map(jnp.zeros_like, accum)
            return updates, _AccumState(jnp.zeros((), jnp.int32), zeroed,
                                        inner)

        def skip(_):
            updates = jax.tree.map(jnp.zeros_like, grads)
            return updates, _AccumState(state.counter + 1, accum, state.inner)

        return jax.lax.cond(is_sync, do_sync, skip, None)

    update._hvd_allreduce = True
    return optax.GradientTransformation(init, update)


def DistributedAdasumOptimizer(optimizer: optax.GradientTransformation,
                               **kwargs) -> optax.GradientTransformation:
    """Adasum variant (``_DistributedAdasumOptimizer`` parity)."""
    from ..collectives.reduce_op import Adasum
    kwargs["op"] = Adasum
    return DistributedOptimizer(optimizer, **kwargs)


# --- elastic resize -------------------------------------------------------

def ef_resize_residuals(residuals, params, old_world: int, new_world: int,
                        *, fusion_threshold: Optional[int] = None,
                        compression=None):
    """Re-bucket an ``_EFState`` residual carry for a new world size.

    EF bucket shapes depend only on the fusion threshold (world-
    independent), so a rank change only changes the leading world axis.
    The dropped ranks' pending correction mass is NOT lost: with the
    exchange averaging over ``world``, the carried quantity is
    ``sum(residuals) / world``, so the kept rows are rescaled by
    ``new/old`` and each dropped row's mass is spread uniformly::

        res'_i = (new/old) * res_i + sum(dropped) / old

    which preserves ``sum(res') / new == sum(res) / old`` exactly (same
    algebra when growing: the existing rows are rescaled and new rows
    start at zero).  Residuals are zeroed -- with a counted warning --
    only when the bucket plan itself is irreconcilable (different bucket
    count or sizes, e.g. the fusion threshold changed across the
    restart).

    Returns ``(new_residuals, report)``.
    """
    import logging
    import numpy as np
    logger = logging.getLogger("horovod_tpu.optim")
    old_world, new_world = int(old_world), int(new_world)
    report = {"carried_bytes": 0, "zeroed_buckets": 0}
    expected = None
    if params is not None:
        comp = _resolve_compression(compression)
        spec = ef_bucket_plan(jax.tree.leaves(params), fusion_threshold,
                              comp)
        # Row shape under the NEW mesh: flat codecs (size,), per-leg
        # codecs (2, shard) -- a slice-boundary resize that changes the
        # shard width shows up here as an irreconcilable shape and the
        # residual is zeroed (counted) rather than silently misaligned.
        expected = [ef_residual_shape(sum(s.size for s in lspecs), comp)
                    for _dt, lspecs in spec.buffers]

    def _zeroed(shape):
        from ..optim.zero import _count_zeroed_residual
        _count_zeroed_residual()
        report["zeroed_buckets"] += 1
        return jnp.zeros((new_world,) + tuple(shape), jnp.float32)

    res_list = list(residuals)
    if expected is not None and len(res_list) != len(expected):
        logger.warning(
            "ef_resize_residuals: carry has %d bucket(s) but the plan "
            "for the new world has %d -- zeroing all residuals",
            len(res_list), len(expected))
        return tuple(_zeroed(s) for s in expected), report

    out = []
    for i, r in enumerate(res_list):
        arr = np.asarray(jax.device_get(r), dtype=np.float32)
        shape = tuple(expected[i]) if expected is not None else (
            arr.shape[1:] if arr.ndim >= 2 else None)
        if arr.ndim < 2 or shape is None or arr.shape[1:] != shape:
            logger.warning(
                "ef_resize_residuals: bucket %d shape %s irreconcilable "
                "with planned row shape %s -- zeroing it", i,
                getattr(arr, "shape", None), shape)
            out.append(_zeroed(shape if shape is not None else (0,)))
            continue
        rows = arr.shape[0]
        keep = min(rows, new_world)
        newr = np.zeros((new_world,) + shape, np.float32)
        newr[:keep] = arr[:keep] * (new_world / rows)
        if rows > new_world:
            newr += arr[new_world:].sum(axis=0) / rows
        out.append(jnp.asarray(newr))
        report["carried_bytes"] += int(arr.nbytes)
    return tuple(out), report
