"""State-synchronization helpers.

Parity with ``horovod/torch/functions.py``: ``broadcast_parameters``,
``broadcast_optimizer_state``, ``broadcast_object`` -- the rank-0-saves /
everyone-restores idiom used on (re)start and by elastic ``state.sync()``.
"""

from __future__ import annotations

import pickle
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..collectives import eager as _eager
from ..core import process_sets as _ps


def _one_row(out) -> np.ndarray:
    return _eager.one_row(out)


def broadcast_(tree: Any, root_rank: int = 0, *, process_set=None) -> Any:
    """Broadcast every array leaf of a pytree from ``root_rank``.

    Works on replicated host-side values: each worker contributes its copy,
    everyone leaves with root's.  Array leaves are FUSED per dtype into one
    flat buffer and broadcast with a single collective per dtype (the
    fusion-buffer idiom) -- a per-leaf loop would compile one XLA program
    per distinct shape, minutes of tunnel compile time for a real model.
    Non-array leaves (ints, None, ...) pass through
    :func:`broadcast_object`.
    """
    ps = _ps.get_process_set(process_set)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out_leaves = list(leaves)
    arr_idx = [i for i, leaf in enumerate(leaves)
               if isinstance(leaf, (jax.Array, np.ndarray))
               or hasattr(leaf, "dtype")]
    arr_set = set(arr_idx)
    for i, leaf in enumerate(leaves):
        if i not in arr_set:
            out_leaves[i] = broadcast_object(leaf, root_rank, process_set=ps)
    rows = _eager.broadcast_fused([leaves[i] for i in arr_idx], root_rank,
                                  name="broadcast.tree", process_set=ps)
    for i, row in zip(arr_idx, rows):
        out_leaves[i] = jnp.asarray(row)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


def broadcast_parameters(params: Any, root_rank: int = 0, *,
                         process_set=None) -> Any:
    """``hvd.broadcast_parameters`` parity: sync model params from root."""
    return broadcast_(params, root_rank, process_set=process_set)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0, *,
                              process_set=None) -> Any:
    """``hvd.broadcast_optimizer_state`` parity."""
    return broadcast_(opt_state, root_rank, process_set=process_set)


def broadcast_object(obj: Any, root_rank: int = 0, *,
                     process_set=None) -> Any:
    """Pickle-broadcast an arbitrary Python object from ``root_rank``.

    Two-phase (size then padded payload) so processes with different local
    values agree on buffer shape, as the reference does with its
    size-prefixed byte stream.
    """
    ps = _ps.get_process_set(process_set)
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    size = np.array([len(payload)], dtype=np.int32)
    gsize = int(_one_row(_eager.broadcast(
        _eager.replicated_stack(size, ps), root_rank, process_set=ps))[0])
    buf = np.zeros(gsize, dtype=np.uint8)
    buf[:min(len(payload), gsize)] = payload[:gsize]
    out = _one_row(_eager.broadcast(
        _eager.replicated_stack(buf, ps), root_rank, process_set=ps))
    return pickle.loads(out.tobytes())


def allgather_object(obj: Any, *, name=None, process_set=None) -> list:
    """Gather one picklable object per rank; all ranks receive the
    rank-ordered list (``horovod/torch/functions.py::allgather_object``).

    Byte payloads ride the ragged allgather (sizes exchanged first, like
    the reference's size-prefixed gather); single-controller mode returns
    ``size()`` copies of the local object.
    """
    import io

    ps = _ps.get_process_set(process_set)
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # One ragged gather is enough: allgatherv exchanges sizes internally,
    # and pickle streams are self-delimiting, so the concatenation splits
    # itself back into per-rank objects.
    data = _eager.allgather_value(payload, name=name, process_set=ps)
    buf = io.BytesIO(np.asarray(data).tobytes())
    out = []
    while buf.tell() < len(buf.getbuffer()):
        out.append(pickle.load(buf))
    return out
