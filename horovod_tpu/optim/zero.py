"""ZeRO-1 sharded optimizer state (Rajbhandari et al., 2020).

The replicated step moves the whole gradient payload through one fused
allreduce and every chip runs the full optimizer update on a full copy of
the optimizer state.  ZeRO stage 1 splits that work across the
data-parallel mesh:

* gradients are packed into flat per-dtype **arenas** (the fusion-buffer
  idea, but padded so the mesh size divides each arena) and exchanged with
  one ``reduce-scatter`` per arena -- each chip receives the fully-reduced
  mean of its own 1/n slice only;
* each chip runs ``optimizer.update`` on its slice of the param/opt-state
  arena, so optimizer-update FLOPs and optimizer-state HBM both shrink by
  the mesh size;
* the updated param shards are broadcast back with one ``all-gather`` per
  arena, optionally compressed through the existing
  :mod:`~horovod_tpu.collectives.compression` codecs (fp16/bf16 cast the
  wire; fp8 quantizes per shard and gathers e4m3 bytes + one f32 scale per
  shard).  Every chip dequantizes the SAME wire bytes -- its own shard
  included -- so replicas stay bit-identical.

Wire math: an uncompressed reduce-scatter + all-gather moves exactly the
bytes of one ring allreduce (2B(n-1)/n per chip); the ZeRO win is the /n
optimizer FLOPs + HBM and the *compressible* allgather leg (fp16 gather:
0.75x the replicated wire; fp8: 0.625x).

Layout contract: the sharded optimizer state is the inner optimizer's
state over the list of arena shards, with every leaf carrying a leading
``[n, ...]`` axis that shards over the mesh (``PartitionSpec(axes)``).
Plain pytree of arrays, so it round-trips through
:func:`horovod_tpu.save_checkpoint` / ``restore_checkpoint`` unchanged;
re-place a restored (replicated) state onto the mesh with
:func:`shard_zero_state`.

Use the BARE optax optimizer with ``zero_stage=1`` -- the reduce-scatter
replaces :func:`~horovod_tpu.optim.distributed.DistributedOptimizer`'s
allreduce, and wrapping would re-reduce already-disjoint shard gradients
(detected and rejected).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..collectives import ops as _ops
from ..collectives.compression import (Compression, fp8_quantize, is_fp8)
from ..collectives.reduce_op import Average
from ..controller.fusion import _LeafSpec


@dataclasses.dataclass(frozen=True)
class _ArenaBuffer:
    """One flat per-dtype buffer of the ZeRO arena."""
    dtype: Any
    leaves: Tuple[_LeafSpec, ...]
    size: int      # unpadded element count
    padded: int    # padded so ``world`` divides it
    shard: int     # padded // world


@dataclasses.dataclass(frozen=True)
class ZeroSpec:
    """Static flatten/partition plan: how a pytree maps onto the arenas.

    Deterministic in (tree structure, leaf shapes/dtypes, world size), so
    the plan computed at ``zero_init`` time and the one recomputed inside
    the traced step agree without being carried through the state.
    """
    buffers: Tuple[_ArenaBuffer, ...]
    num_leaves: int
    world: int


def plan_arena(leaves: Sequence[Any], world: int) -> ZeroSpec:
    """One arena per dtype (leaf order preserved), padded to ``world``."""
    by_dtype: dict = {}
    for i, x in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(x.dtype), []).append(
            _LeafSpec(i, tuple(x.shape),
                      int(np.prod(x.shape, dtype=np.int64))))
    buffers = []
    for dt, specs in by_dtype.items():
        size = sum(s.size for s in specs)
        padded = int(math.ceil(size / world)) * world if size else 0
        buffers.append(_ArenaBuffer(dtype=dt, leaves=tuple(specs),
                                    size=size, padded=padded,
                                    shard=padded // world))
    return ZeroSpec(buffers=tuple(buffers), num_leaves=len(leaves),
                    world=world)


def arena_pack(leaves: Sequence[jax.Array], spec: ZeroSpec
               ) -> List[jax.Array]:
    """Ravel+concat leaves into the padded flat arenas."""
    out = []
    for buf in spec.buffers:
        parts = [jnp.ravel(leaves[s.index]) for s in buf.leaves]
        pad = buf.padded - buf.size
        if pad:
            parts.append(jnp.zeros((pad,), buf.dtype))
        out.append(parts[0] if len(parts) == 1 else jnp.concatenate(parts))
    return out


def arena_unpack(arenas: Sequence[jax.Array], spec: ZeroSpec
                 ) -> List[jax.Array]:
    """Slice the (padding dropped) arenas back into the leaf list."""
    leaves: List[Optional[jax.Array]] = [None] * spec.num_leaves
    for arena, buf in zip(arenas, spec.buffers):
        off = 0
        for s in buf.leaves:
            leaves[s.index] = arena[off:off + s.size].reshape(s.shape)
            off += s.size
    assert all(l is not None for l in leaves)
    return leaves  # type: ignore[return-value]


def _reject_distributed(optimizer) -> None:
    if getattr(optimizer.update, "_hvd_allreduce", False):
        raise ValueError(
            "zero_stage=1 replaces the gradient allreduce with a "
            "reduce-scatter; pass the bare optax optimizer, not "
            "DistributedOptimizer (which would re-reduce disjoint shard "
            "gradients)")


def compressed_allgather(x, *, axes, compression=None):
    """All-gather ``x`` (each worker's shard) with an optional wire codec.

    fp16/bf16 cast the shard down for the wire and back up after; fp8
    quantizes per shard (e4m3 + one f32 scale each) and dequantizes every
    gathered shard from the wire bytes -- the sender's own shard included,
    so all replicas reconstruct identical values.  Non-floating or
    already-narrow shards gather uncompressed.
    """
    comp = compression or Compression.none
    if is_fp8(comp):
        if (not jnp.issubdtype(x.dtype, jnp.floating)
                or jnp.dtype(x.dtype).itemsize <= 1):
            return _ops.allgather(x, axes=axes)
        q, scale = fp8_quantize(x)
        full_q = _ops.allgather(q, axes=axes)            # [n * shard] e4m3
        scales = _ops.allgather(scale.reshape(1), axes=axes)  # [n] f32
        n = scales.shape[0]
        full = full_q.astype(jnp.float32).reshape(n, -1) * scales[:, None]
        return full.reshape(-1).astype(x.dtype)
    wire, ctx = comp.compress(x)
    return comp.decompress(_ops.allgather(wire, axes=axes), ctx)


def _use_reducescatter() -> bool:
    """Trace-time exchange choice.  Default: reduce-scatter.  When the
    autotuner's zero axis is being searched (``HOROVOD_AUTOTUNE_ZERO=1``
    on a zero-configured run), the sample's axis value picks between the
    reduce-scatter exchange (1) and the allreduce exchange (0) over the
    same sharded arena -- the score loop measures both wire profiles and
    locks the winner per model."""
    from ..core.state import global_state
    tuner = global_state().autotuner
    if tuner is not None and getattr(tuner, "tunes_zero", False):
        return bool(tuner.zero_stage())
    return True


def _resolve_compression(compression):
    comp = compression or Compression.none
    from ..core.state import global_state
    tuner = global_state().autotuner
    if tuner is not None:
        comp = tuner.compression_override(comp)
    return comp


def zero_apply(optimizer, grads, zero_state, params, *, axes,
               compression=None):
    """Sharded exchange + shard-local update (call inside ``shard_map``).

    Returns ``(new_params, new_zero_state)``; ``new_params`` is the full
    (replicated) tree reassembled from the compressed allgather,
    ``new_zero_state`` keeps the leading ``[1, ...]`` local axis that
    shards over the mesh.
    """
    _reject_distributed(optimizer)
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return params, zero_state
    p_leaves = jax.tree.leaves(params)
    n = _ops.axis_size(axes)
    spec = plan_arena(leaves, n)
    g_arenas = arena_pack(leaves, spec)
    p_arenas = arena_pack(p_leaves, spec)
    idx = _ops.axis_index(axes)
    use_rs = _use_reducescatter()
    g_shards, p_shards = [], []
    for g, p, buf in zip(g_arenas, p_arenas, spec.buffers):
        if use_rs:
            gs = _ops.reducescatter(g, Average, axes=axes)
        else:
            red = _ops.allreduce(g, Average, axes=axes)
            gs = lax.dynamic_slice_in_dim(red, idx * buf.shard, buf.shard, 0)
        g_shards.append(gs)
        p_shards.append(
            lax.dynamic_slice_in_dim(p, idx * buf.shard, buf.shard, 0))
    inner = jax.tree.map(lambda v: v[0], zero_state)
    updates, inner = optimizer.update(g_shards, inner, p_shards)
    import optax
    p_shards = optax.apply_updates(p_shards, updates)
    comp = _resolve_compression(compression)
    full = [compressed_allgather(s, axes=axes, compression=comp)
            for s in p_shards]
    new_params = jax.tree.unflatten(treedef, arena_unpack(full, spec))
    return new_params, jax.tree.map(lambda v: v[None], inner)


def zero_init(optimizer, params, mesh: Optional[Mesh] = None):
    """Build the sharded optimizer state for ``zero_stage=1``.

    Each device runs ``optimizer.init`` on its own arena shard; the
    result's leaves carry a leading ``[n, ...]`` axis sharded over the
    mesh, so the state occupies 1/n of the replicated state's HBM per
    chip.  Pass the result as the ``opt_state`` of a step built with
    ``make_train_step(..., zero_stage=1)``.
    """
    from ..core import basics as _basics
    _reject_distributed(optimizer)
    mesh = mesh or _basics.mesh()
    axes = tuple(mesh.axis_names)
    world = int(np.prod(mesh.devices.shape))

    def local_init(params):
        leaves = jax.tree.leaves(params)
        spec = plan_arena(leaves, world)
        arenas = arena_pack(leaves, spec)
        idx = _ops.axis_index(axes)
        shards = [lax.dynamic_slice_in_dim(a, idx * b.shard, b.shard, 0)
                  for a, b in zip(arenas, spec.buffers)]
        inner = optimizer.init(shards)
        return jax.tree.map(lambda v: jnp.asarray(v)[None], inner)

    fn = jax.shard_map(local_init, mesh=mesh, in_specs=(P(),),
                       out_specs=P(axes), check_vma=False)
    return jax.jit(fn)(params)


def zero_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """The sharding of every zero-state leaf (leading axis over the mesh)."""
    from ..core import basics as _basics
    mesh = mesh or _basics.mesh()
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def shard_zero_state(state, mesh: Optional[Mesh] = None):
    """Place a (restored, host/replicated) zero state onto the mesh.

    ``restore_checkpoint`` returns replicated leaves; the step expects
    them sharded on the leading axis -- this re-places every leaf.
    """
    sh = zero_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), state)


def zero_report(optimizer, params, world: int, compression=None) -> dict:
    """Static wire/HBM accounting for the zero1 config (bench surface).

    Returns per-chip link bytes per step for the gradient reduce-scatter
    and the (possibly compressed) param allgather, the replicated
    allreduce equivalent, and optimizer-state HBM per chip for both
    layouts.  Pure shape arithmetic -- nothing is materialized.
    """
    leaves = jax.tree.leaves(params)
    spec = plan_arena(leaves, world)
    comp = compression or Compression.none

    def wire_itemsize(dt) -> int:
        dt = jnp.dtype(dt)
        if not jnp.issubdtype(dt, jnp.floating):
            return dt.itemsize
        if is_fp8(comp):
            return 1 if dt.itemsize > 1 else dt.itemsize
        wd = getattr(comp, "wire_dtype", None)
        if wd is not None and dt.itemsize > jnp.dtype(wd).itemsize:
            return jnp.dtype(wd).itemsize
        return dt.itemsize

    rs = sum(b.padded * jnp.dtype(b.dtype).itemsize
             for b in spec.buffers) * (world - 1) // max(world, 1)
    ag = sum(b.padded * wire_itemsize(b.dtype)
             for b in spec.buffers) * (world - 1) // max(world, 1)
    if is_fp8(comp):
        ag += 4 * world * len(spec.buffers)  # one f32 scale per shard
    full_bytes = sum(b.padded * jnp.dtype(b.dtype).itemsize
                     for b in spec.buffers)
    allreduce_eq = 2 * full_bytes * (world - 1) // max(world, 1)
    shards = [jax.ShapeDtypeStruct((b.shard,), b.dtype)
              for b in spec.buffers]
    state = jax.eval_shape(optimizer.init, shards)
    opt_shard_bytes = sum(l.size * jnp.dtype(l.dtype).itemsize
                          for l in jax.tree.leaves(state))
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            tuple(getattr(x, "shape", np.shape(x))),
            jnp.dtype(getattr(x, "dtype", None) or np.asarray(x).dtype)),
        params)
    full_state = jax.eval_shape(optimizer.init, abstract)
    opt_full_bytes = sum(l.size * jnp.dtype(l.dtype).itemsize
                         for l in jax.tree.leaves(full_state))
    return {
        "world": world,
        "reducescatter_bytes_per_chip": int(rs),
        "allgather_bytes_per_chip": int(ag),
        "zero1_exchanged_bytes_per_chip": int(rs + ag),
        "replicated_allreduce_bytes_per_chip": int(allreduce_eq),
        "opt_state_bytes_per_chip_zero1": int(opt_shard_bytes),
        "opt_state_bytes_per_chip_replicated": int(opt_full_bytes),
    }
