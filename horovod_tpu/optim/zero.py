"""ZeRO-1 sharded optimizer state (Rajbhandari et al., 2020).

The replicated step moves the whole gradient payload through one fused
allreduce and every chip runs the full optimizer update on a full copy of
the optimizer state.  ZeRO stage 1 splits that work across the
data-parallel mesh:

* gradients are packed into flat per-dtype **arenas** (the fusion-buffer
  idea, but padded so the mesh size divides each arena) and exchanged with
  one ``reduce-scatter`` per arena -- each chip receives the fully-reduced
  mean of its own 1/n slice only;
* each chip runs ``optimizer.update`` on its slice of the param/opt-state
  arena, so optimizer-update FLOPs and optimizer-state HBM both shrink by
  the mesh size;
* the updated param shards are broadcast back with one ``all-gather`` per
  arena, optionally compressed through the existing
  :mod:`~horovod_tpu.collectives.compression` codecs (fp16/bf16 cast the
  wire; fp8 quantizes per shard and gathers e4m3 bytes + one f32 scale per
  shard).  Every chip dequantizes the SAME wire bytes -- its own shard
  included -- so replicas stay bit-identical.

Wire math: an uncompressed reduce-scatter + all-gather moves exactly the
bytes of one ring allreduce (2B(n-1)/n per chip); the ZeRO win is the /n
optimizer FLOPs + HBM and the *compressible* allgather leg (fp16 gather:
0.75x the replicated wire; fp8: 0.625x).

Layout contract: the sharded optimizer state is the inner optimizer's
state over the list of arena shards, with every leaf carrying a leading
``[n, ...]`` axis that shards over the mesh (``PartitionSpec(axes)``).
Plain pytree of arrays, so it round-trips through
:func:`horovod_tpu.save_checkpoint` / ``restore_checkpoint`` unchanged;
re-place a restored (replicated) state onto the mesh with
:func:`shard_zero_state`.

Use the BARE optax optimizer with ``zero_stage=1`` -- the reduce-scatter
replaces :func:`~horovod_tpu.optim.distributed.DistributedOptimizer`'s
allreduce, and wrapping would re-reduce already-disjoint shard gradients
(detected and rejected).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..collectives import ops as _ops
from ..collectives.compression import (Compression, fp8_quantize, is_fp8,
                                       is_error_feedback, is_hier_legs,
                                       is_powersgd, parse_compression,
                                       powersgd_factor_widths,
                                       powersgd_matrix_shape, topk_count)
from ..collectives.reduce_op import Average
from ..controller.fusion import _LeafSpec


class _ZeroEFState(NamedTuple):
    """ZeRO-1 state carry when ``zero_compression`` is an error-feedback
    codec: the shard-owner residuals ride NEXT TO the inner state with the
    same leading ``[n, ...]`` sharded axis ("residuals live on the shard
    owner" -- each rank's residual covers only the arena slice it
    allgathers, 1/n of the replicated EF footprint)."""
    residuals: Any                # tuple of [n, shard] f32, one per arena
    inner: Any                    # sharded inner optimizer state


@dataclasses.dataclass(frozen=True)
class _ArenaBuffer:
    """One flat per-dtype buffer of the ZeRO arena."""
    dtype: Any
    leaves: Tuple[_LeafSpec, ...]
    size: int      # unpadded element count
    padded: int    # padded so ``world`` divides it
    shard: int     # padded // world


@dataclasses.dataclass(frozen=True)
class ZeroSpec:
    """Static flatten/partition plan: how a pytree maps onto the arenas.

    Deterministic in (tree structure, leaf shapes/dtypes, world size), so
    the plan computed at ``zero_init`` time and the one recomputed inside
    the traced step agree without being carried through the state.
    """
    buffers: Tuple[_ArenaBuffer, ...]
    num_leaves: int
    world: int


def plan_arena(leaves: Sequence[Any], world: int) -> ZeroSpec:
    """One arena per dtype (leaf order preserved), padded to ``world``."""
    by_dtype: dict = {}
    for i, x in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(x.dtype), []).append(
            _LeafSpec(i, tuple(x.shape),
                      int(np.prod(x.shape, dtype=np.int64))))
    buffers = []
    for dt, specs in by_dtype.items():
        size = sum(s.size for s in specs)
        padded = int(math.ceil(size / world)) * world if size else 0
        buffers.append(_ArenaBuffer(dtype=dt, leaves=tuple(specs),
                                    size=size, padded=padded,
                                    shard=padded // world))
    return ZeroSpec(buffers=tuple(buffers), num_leaves=len(leaves),
                    world=world)


def arena_pack(leaves: Sequence[jax.Array], spec: ZeroSpec
               ) -> List[jax.Array]:
    """Ravel+concat leaves into the padded flat arenas."""
    out = []
    for buf in spec.buffers:
        parts = [jnp.ravel(leaves[s.index]) for s in buf.leaves]
        pad = buf.padded - buf.size
        if pad:
            parts.append(jnp.zeros((pad,), buf.dtype))
        out.append(parts[0] if len(parts) == 1 else jnp.concatenate(parts))
    return out


def arena_unpack(arenas: Sequence[jax.Array], spec: ZeroSpec
                 ) -> List[jax.Array]:
    """Slice the (padding dropped) arenas back into the leaf list."""
    leaves: List[Optional[jax.Array]] = [None] * spec.num_leaves
    for arena, buf in zip(arenas, spec.buffers):
        off = 0
        for s in buf.leaves:
            leaves[s.index] = arena[off:off + s.size].reshape(s.shape)
            off += s.size
    assert all(l is not None for l in leaves)
    return leaves  # type: ignore[return-value]


def _reject_distributed(optimizer) -> None:
    if getattr(optimizer.update, "_hvd_allreduce", False):
        raise ValueError(
            "zero_stage=1 replaces the gradient allreduce with a "
            "reduce-scatter; pass the bare optax optimizer, not "
            "DistributedOptimizer (which would re-reduce disjoint shard "
            "gradients)")


def compressed_allgather(x, *, axes, compression=None):
    """All-gather ``x`` (each worker's shard) with an optional wire codec.

    fp16/bf16 cast the shard down for the wire and back up after; fp8
    quantizes per shard (e4m3 + one f32 scale each) and dequantizes every
    gathered shard from the wire bytes -- the sender's own shard included,
    so all replicas reconstruct identical values.  Non-floating or
    already-narrow shards gather uncompressed.
    """
    comp = compression or Compression.none
    if is_fp8(comp):
        if (not jnp.issubdtype(x.dtype, jnp.floating)
                or jnp.dtype(x.dtype).itemsize <= 1):
            return _ops.allgather(x, axes=axes)
        q, scale = fp8_quantize(x)
        full_q = _ops.allgather(q, axes=axes)            # [n * shard] e4m3
        scales = _ops.allgather(scale.reshape(1), axes=axes)  # [n] f32
        n = scales.shape[0]
        full = full_q.astype(jnp.float32).reshape(n, -1) * scales[:, None]
        return full.reshape(-1).astype(x.dtype)
    wire, ctx = comp.compress(x)
    return comp.decompress(_ops.allgather(wire, axes=axes), ctx)


def ef_delta_allgather(delta, *, axes, compression):
    """Compressed allgather of each shard owner's param DELTA (the EF
    composition of the ZeRO allgather leg).

    ``delta`` is this rank's flat f32 update (new shard - old shard, plus
    the fed-back residual).  Each rank compresses its OWN delta locally --
    PowerSGD here is a plain local low-rank factorization (one
    orthogonalization round, no inner collective: there is nothing to
    reduce, each shard has one owner) and top-k keeps the largest
    magnitudes -- then ONE allgather moves the compressed payloads and
    EVERY rank reconstructs EVERY shard's delta from the same wire bytes
    (sender included), so replicas stay bit-identical, exactly the
    ``compressed_allgather`` fp8 contract.

    Returns ``(full, own)``: ``full`` is the ``[n, shard]`` f32
    reconstruction of all shards' deltas, ``own`` this rank's row (what
    the mesh actually applied for it -- the EF residual is
    ``delta - own``).
    """
    n = _ops.axis_size(axes)
    my = _ops.axis_index(axes)
    shard = delta.shape[0]
    if is_powersgd(compression):
        m, c = powersgd_matrix_shape(shard)
        pad = m * c - shard
        flat = jnp.concatenate([delta, jnp.zeros((pad,), jnp.float32)]) \
            if pad else delta
        mat = flat.reshape(m, c)
        r = max(1, min(int(compression.rank), m, c))
        p = _ops._orthonormalize_columns(mat @ _ops._powersgd_seed_matrix(c, r))
        q = mat.T @ p                                  # [c, r]
        wire = jnp.concatenate([p.ravel(), q.ravel()])  # [r*(m+c)]
        gw = _ops._gather_rows(wire, axes)             # [n, r*(m+c)]
        ps = gw[:, :r * m].reshape(n, m, r)
        qs = gw[:, r * m:].reshape(n, c, r)
        full = jnp.einsum("nmr,ncr->nmc", ps, qs).reshape(n, -1)[:, :shard]
    else:
        k = min(topk_count(shard, compression.fraction), shard)
        _, idx = lax.top_k(jnp.abs(delta), k)
        vals = jnp.take(delta, idx)
        gv = _ops._gather_rows(vals, axes)             # [n, k]
        gi = _ops._gather_rows(idx, axes)              # [n, k]
        pos = gi + (jnp.arange(n, dtype=gi.dtype) * shard)[:, None]
        full = jnp.zeros((n * shard,), jnp.float32).at[
            pos.ravel()].set(gv.ravel()).reshape(n, shard)
    own = jnp.take(full, my, axis=0)
    return full, own


def _use_reducescatter() -> bool:
    """Trace-time exchange choice.  Default: reduce-scatter.  When the
    autotuner's zero axis is being searched (``HOROVOD_AUTOTUNE_ZERO=1``
    on a zero-configured run), the sample's axis value picks between the
    reduce-scatter exchange (1) and the allreduce exchange (0) over the
    same sharded arena -- the score loop measures both wire profiles and
    locks the winner per model."""
    from ..core.state import global_state
    tuner = global_state().autotuner
    if tuner is not None and getattr(tuner, "tunes_zero", False):
        return bool(tuner.zero_stage())
    return True


def _resolve_compression(compression):
    comp = parse_compression(compression) if compression else Compression.none
    from ..core.state import global_state
    tuner = global_state().autotuner
    if tuner is not None:
        override = tuner.compression_override(comp)
        # The tuner may not flip EF-ness mid-run: the ZeRO state layout
        # (whether residuals ride next to the inner state) was fixed at
        # zero_init time.
        if is_error_feedback(override) == is_error_feedback(comp):
            comp = override
    return comp


def zero_apply(optimizer, grads, zero_state, params, *, axes,
               compression=None):
    """Sharded exchange + shard-local update (call inside ``shard_map``).

    Returns ``(new_params, new_zero_state)``; ``new_params`` is the full
    (replicated) tree reassembled from the compressed allgather,
    ``new_zero_state`` keeps the leading ``[1, ...]`` local axis that
    shards over the mesh.

    With an error-feedback ``compression`` (powersgd/topk) the allgather
    leg moves each owner's compressed param DELTA instead of the raw
    shard (:func:`ef_delta_allgather`); ``zero_state`` must then be the
    :class:`_ZeroEFState` built by ``zero_init(..., compression=...)``.
    """
    _reject_distributed(optimizer)
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return params, zero_state
    comp = _resolve_compression(compression)
    ef = is_error_feedback(comp)
    if ef:
        if not isinstance(zero_state, _ZeroEFState):
            if (isinstance(zero_state, (tuple, list))
                    and len(zero_state) == 2):
                zero_state = _ZeroEFState(*zero_state)  # restored carry
            else:
                raise ValueError(
                    "zero_compression=powersgd/topk needs the residual-"
                    "carrying state from zero_init(..., compression=...); "
                    f"got {type(zero_state).__name__}")
        residuals = tuple(r[0] for r in zero_state.residuals)
        inner_full = zero_state.inner
    else:
        inner_full = zero_state
    p_leaves = jax.tree.leaves(params)
    n = _ops.axis_size(axes)
    spec = plan_arena(leaves, n)
    g_arenas = arena_pack(leaves, spec)
    p_arenas = arena_pack(p_leaves, spec)
    idx = _ops.axis_index(axes)
    use_rs = _use_reducescatter()
    ax = tuple((axes,) if isinstance(axes, str) else axes)
    hier = is_hier_legs(comp) and len(ax) == 2
    if hier:
        # Per-leg codec on the two-level mesh: intra-slice RS FIRST so
        # only the 1/n_ici shard ever crosses DCN, compressed leader
        # exchange over the slice axis, allgather back in the inverse
        # order.  The rank->shard bijection becomes (ici, dcn)-major to
        # match that scatter order -- a bijection either way, so pack/
        # unpack stay consistent as long as the same index is used
        # throughout (zero_init mirrors it).
        dcn_ax, ici_ax = ax
        rs_axes = (ici_ax, dcn_ax)
        idx = (lax.axis_index(ici_ax) * lax.axis_size(dcn_ax)
               + lax.axis_index(dcn_ax))
    else:
        rs_axes = axes
    # Trace-time leg registration (fires once per trace, like
    # _note_compression_ratio): attributes the compiled step's exchange
    # bytes to the ZeRO RS/AG legs for the cross-rank straggler report.
    # The RS/AG rows come from the shared exchange-plan IR -- this
    # executor only picks which collective to run per row.
    from ..controller import fusion as _fusion
    from ..timeline import spans as _spans
    zplan = _fusion.plan_exchange(
        "zero",
        buffers=tuple((str(jnp.dtype(b.dtype)), int(b.size),
                       int(b.padded), int(b.shard)) for b in spec.buffers),
        world=int(n), compression=comp,
        axes_shape=(tuple(int(lax.axis_size(a)) for a in ax)
                    if len(ax) == 2 else None),
        axes=(ax if len(ax) == 2 else ()), use_rs=use_rs)
    rs_legs = zplan.legs[:len(spec.buffers)]
    ag_legs = zplan.legs[len(spec.buffers):]
    g_shards, p_shards = [], []
    for i, (g, p, buf) in enumerate(zip(g_arenas, p_arenas, spec.buffers)):
        _spans.note_leg(rs_legs[i], bucket_id=i)
        if use_rs:
            gs = _ops.reducescatter(g, Average, axes=rs_axes)
        else:
            red = _ops.allreduce(g, Average, axes=axes)
            gs = lax.dynamic_slice_in_dim(red, idx * buf.shard, buf.shard, 0)
        g_shards.append(gs)
        p_shards.append(
            lax.dynamic_slice_in_dim(p, idx * buf.shard, buf.shard, 0))
    inner = jax.tree.map(lambda v: v[0], inner_full)
    old_shards = p_shards
    updates, inner = optimizer.update(g_shards, inner, p_shards)
    import optax
    p_shards = optax.apply_updates(p_shards, updates)
    if ef:
        from .distributed import _ef_enabled
        feed = _ef_enabled()
        full, new_res = [], []
        for i, (old, new, res, arena, buf) in enumerate(zip(
                old_shards, p_shards, residuals, p_arenas, spec.buffers)):
            _spans.note_leg(ag_legs[i], bucket_id=i)
            if (not jnp.issubdtype(buf.dtype, jnp.floating)
                    or buf.shard < 1):
                full.append(_ops.allgather(new, axes=rs_axes))
                new_res.append(res)
                continue
            delta = (new.astype(jnp.float32) - old.astype(jnp.float32))
            if feed:
                delta = delta + res
            recon, own = ef_delta_allgather(
                delta, axes=rs_axes,
                compression=comp.dcn if hier else comp)
            full.append(
                (arena.astype(jnp.float32) + recon.ravel())
                .astype(buf.dtype))
            new_res.append(delta - own if feed else res)
        new_params = jax.tree.unflatten(treedef, arena_unpack(full, spec))
        return new_params, _ZeroEFState(
            tuple(r[None] for r in new_res),
            jax.tree.map(lambda v: v[None], inner))
    full = []
    for i, s in enumerate(p_shards):
        _spans.note_leg(ag_legs[i], bucket_id=i)
        if hier:
            # Leader exchange over the slice axis rides the DCN codec;
            # the intra-slice reassembly rides the (psum-compatible) ICI
            # codec.
            block = compressed_allgather(s, axes=(dcn_ax,),
                                         compression=comp.dcn)
            full.append(compressed_allgather(block, axes=(ici_ax,),
                                             compression=comp.ici))
        else:
            full.append(compressed_allgather(s, axes=axes, compression=comp))
    new_params = jax.tree.unflatten(treedef, arena_unpack(full, spec))
    return new_params, jax.tree.map(lambda v: v[None], inner)


def zero_init(optimizer, params, mesh: Optional[Mesh] = None,
              compression=None, param_specs=None):
    """Build the sharded optimizer state for ``zero_stage=1``.

    Each device runs ``optimizer.init`` on its own arena shard; the
    result's leaves carry a leading ``[n, ...]`` axis sharded over the
    mesh, so the state occupies 1/n of the replicated state's HBM per
    chip.  Pass the result as the ``opt_state`` of a step built with
    ``make_train_step(..., zero_stage=1)``.

    ``compression`` must name the step's ``zero_compression`` when that is
    an error-feedback codec (powersgd/topk): the returned carry is then a
    :class:`_ZeroEFState` with one zero f32 residual per arena shard,
    sharded like the inner state.  Dtype codecs (fp16/bf16/fp8) carry no
    state and may be omitted here.

    On a model-parallel mesh (``build_3d_mesh`` with ``model``/``pipe``
    axes) pass ``param_specs`` -- the same pytree of ``PartitionSpec``s
    the train step was built with.  The arena is then planned over each
    device's LOCAL (TP/stage-sharded) parameter leaves and sharded over
    the DATA axes only: every (tp, pipe) group owns an independent ZeRO
    arena for its own shard of the model, the state still occupies
    ``1/n_data`` of that group's replicated state per chip, and the
    returned leaves carry a leading axis of the FULL mesh extent (one
    arena row per device, sharded over every mesh axis).
    """
    from ..core import basics as _basics
    from ..parallel.mesh import data_axes as _data_axes
    _reject_distributed(optimizer)
    comp = parse_compression(compression) if compression else Compression.none
    ef = is_error_feedback(comp)
    mesh = mesh or _basics.mesh()
    axes = _data_axes(mesh)
    world = int(np.prod([mesh.shape[a] for a in axes]))

    def local_init(params):
        leaves = jax.tree.leaves(params)
        spec = plan_arena(leaves, world)
        arenas = arena_pack(leaves, spec)
        if is_hier_legs(comp) and len(axes) == 2:
            # Match zero_apply's (ici, dcn)-major shard bijection.
            idx = (lax.axis_index(axes[1]) * lax.axis_size(axes[0])
                   + lax.axis_index(axes[0]))
        else:
            idx = _ops.axis_index(axes)
        shards = [lax.dynamic_slice_in_dim(a, idx * b.shard, b.shard, 0)
                  for a, b in zip(arenas, spec.buffers)]
        inner = optimizer.init(shards)
        out = jax.tree.map(lambda v: jnp.asarray(v)[None], inner)
        if ef:
            return _ZeroEFState(
                residuals=tuple(jnp.zeros((1, b.shard), jnp.float32)
                                for b in spec.buffers),
                inner=out)
        return out

    p_spec = param_specs if param_specs is not None else P()
    fn = jax.shard_map(local_init, mesh=mesh, in_specs=(p_spec,),
                       out_specs=P(tuple(mesh.axis_names)),
                       check_vma=False)
    return jax.jit(fn)(params)


def zero_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """The sharding of every zero-state leaf (leading axis over the mesh)."""
    from ..core import basics as _basics
    mesh = mesh or _basics.mesh()
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def shard_zero_state(state, mesh: Optional[Mesh] = None):
    """Place a (restored, host/replicated) zero state onto the mesh.

    ``restore_checkpoint`` returns replicated leaves; the step expects
    them sharded on the leading axis -- this re-places every leaf.
    """
    sh = zero_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), state)


def zero_report(optimizer, params, world: int, compression=None) -> dict:
    """Static wire/HBM accounting for the zero1 config (bench surface).

    Returns per-chip link bytes per step for the gradient reduce-scatter
    and the (possibly compressed) param allgather, the replicated
    allreduce equivalent, and optimizer-state HBM per chip for both
    layouts.  Pure shape arithmetic -- nothing is materialized.
    """
    leaves = jax.tree.leaves(params)
    spec = plan_arena(leaves, world)
    comp = parse_compression(compression) if compression else Compression.none

    def wire_itemsize(dt) -> int:
        dt = jnp.dtype(dt)
        if not jnp.issubdtype(dt, jnp.floating):
            return dt.itemsize
        if is_fp8(comp):
            return 1 if dt.itemsize > 1 else dt.itemsize
        wd = getattr(comp, "wire_dtype", None)
        if wd is not None and dt.itemsize > jnp.dtype(wd).itemsize:
            return jnp.dtype(wd).itemsize
        return dt.itemsize

    rs = sum(b.padded * jnp.dtype(b.dtype).itemsize
             for b in spec.buffers) * (world - 1) // max(world, 1)
    if is_error_feedback(comp):
        # EF delta allgather: each owner's wire is the compressed delta of
        # its shard (factor pair / top-k value+index pairs), not the shard.
        ag = 0
        for b in spec.buffers:
            if (not jnp.issubdtype(jnp.dtype(b.dtype), jnp.floating)
                    or b.shard < 1):
                wire = b.shard * jnp.dtype(b.dtype).itemsize
            elif is_powersgd(comp):
                pw, qw = powersgd_factor_widths(b.shard, comp.rank)
                wire = 4 * (pw + qw)
            else:
                wire = 8 * topk_count(b.shard, comp.fraction)
            ag += wire * world * (world - 1) // max(world, 1)
    else:
        ag = sum(b.padded * wire_itemsize(b.dtype)
                 for b in spec.buffers) * (world - 1) // max(world, 1)
        if is_fp8(comp):
            ag += 4 * world * len(spec.buffers)  # one f32 scale per shard
    full_bytes = sum(b.padded * jnp.dtype(b.dtype).itemsize
                     for b in spec.buffers)
    allreduce_eq = 2 * full_bytes * (world - 1) // max(world, 1)
    shards = [jax.ShapeDtypeStruct((b.shard,), b.dtype)
              for b in spec.buffers]
    state = jax.eval_shape(optimizer.init, shards)
    opt_shard_bytes = sum(l.size * jnp.dtype(l.dtype).itemsize
                          for l in jax.tree.leaves(state))
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            tuple(getattr(x, "shape", np.shape(x))),
            jnp.dtype(getattr(x, "dtype", None) or np.asarray(x).dtype)),
        params)
    full_state = jax.eval_shape(optimizer.init, abstract)
    opt_full_bytes = sum(l.size * jnp.dtype(l.dtype).itemsize
                         for l in jax.tree.leaves(full_state))
    return {
        "world": world,
        "reducescatter_bytes_per_chip": int(rs),
        "allgather_bytes_per_chip": int(ag),
        "zero1_exchanged_bytes_per_chip": int(rs + ag),
        "replicated_allreduce_bytes_per_chip": int(allreduce_eq),
        "opt_state_bytes_per_chip_zero1": int(opt_shard_bytes),
        "opt_state_bytes_per_chip_replicated": int(opt_full_bytes),
    }


# --- elastic resize -------------------------------------------------------

def zero_resize(state, params, old_world: int, new_world: int):
    """Re-lay a ZeRO-1 optimizer state out for a new world size.

    Checkpointless elastic recovery: after a rank loss (or join), the
    flat arenas are re-planned for ``new_world`` and every sharded leaf
    (leading ``[old_world, ...]`` axis) is re-sliced so each survivor
    owns the correct 1/``new_world`` of the SAME flat content -- nothing
    is re-derived, the bytes just move.  ``_ZeroEFState`` residual
    carries index flat arena positions, so re-slicing carries the unsent
    compression mass exactly (only the arena *padding* region, zero for
    top-k and near-zero for powersgd, is dropped when the pad width
    changes).  Per-shard replicated leaves (e.g. an adam step count of
    shape ``[old_world]``) are broadcast from row 0.

    Returns ``(new_state, report)`` with
    ``report = {"carried_bytes", "zeroed_buckets", "resharded",
    "replicated"}``.  Raises ``ValueError`` when a sharded leaf cannot
    be matched to any arena (caller falls back to a full re-derivation).
    """
    import logging
    logger = logging.getLogger("horovod_tpu.optim")
    if params is None:
        raise ValueError("zero_resize needs the params tree to re-plan "
                         "the flat arenas")
    old_world, new_world = int(old_world), int(new_world)
    leaves = jax.tree.leaves(params)
    old_spec = plan_arena(leaves, old_world)
    new_spec = plan_arena(leaves, new_world)
    report = {"carried_bytes": 0, "zeroed_buckets": 0, "resharded": 0,
              "replicated": 0}

    def relayout(arr: np.ndarray, ob: _ArenaBuffer, nb: _ArenaBuffer
                 ) -> np.ndarray:
        flat = arr.reshape(-1)[:ob.size]
        pad = nb.padded - ob.size
        if pad:
            flat = np.concatenate(
                [flat, np.zeros((pad,), dtype=arr.dtype)])
        return flat.reshape(new_world, nb.shard)

    def match_buffer(arr: np.ndarray) -> Optional[int]:
        cands = [i for i, b in enumerate(old_spec.buffers)
                 if b.shard == arr.shape[1]]
        if len(cands) > 1:
            same_dt = [i for i in cands
                       if jnp.dtype(old_spec.buffers[i].dtype)
                       == arr.dtype]
            cands = same_dt or cands
        return cands[0] if len(cands) == 1 else None

    residuals = None
    inner = state
    if isinstance(state, _ZeroEFState):
        inner = state.inner
        res_out = []
        for r, ob, nb in zip(state.residuals, old_spec.buffers,
                             new_spec.buffers):
            arr = np.asarray(jax.device_get(r), dtype=np.float32)
            if arr.ndim == 2 and arr.shape == (old_world, ob.shard):
                res_out.append(jnp.asarray(relayout(arr, ob, nb)))
                report["carried_bytes"] += int(ob.size * 4)
            else:
                logger.warning(
                    "zero_resize: residual carry of shape %s is "
                    "irreconcilable with arena %s/%s -- zeroing it",
                    getattr(arr, "shape", None), ob, nb)
                _count_zeroed_residual()
                res_out.append(
                    jnp.zeros((new_world, nb.shard), jnp.float32))
                report["zeroed_buckets"] += 1
        if len(res_out) < len(new_spec.buffers):
            for nb in new_spec.buffers[len(res_out):]:
                _count_zeroed_residual()
                res_out.append(
                    jnp.zeros((new_world, nb.shard), jnp.float32))
                report["zeroed_buckets"] += 1
        residuals = tuple(res_out)

    def fix_leaf(x):
        arr = np.asarray(jax.device_get(x))
        if arr.ndim >= 1 and arr.shape[0] == old_world:
            if arr.ndim >= 2:
                i = match_buffer(arr)
                if i is not None:
                    report["resharded"] += 1
                    out = relayout(arr, old_spec.buffers[i],
                                   new_spec.buffers[i])
                    report["carried_bytes"] += int(
                        old_spec.buffers[i].size * arr.dtype.itemsize)
                    return jnp.asarray(out)
                raise ValueError(
                    f"zero_resize: sharded leaf of shape {arr.shape} "
                    f"dtype {arr.dtype} matches no arena of the "
                    f"old plan")
            # [old_world] leaf: per-shard replicated content (e.g. the
            # adam step count) -- broadcast row 0 to the new world.
            if not np.all(arr == arr[0]):
                logger.warning(
                    "zero_resize: per-shard scalar rows disagree "
                    "(%s); adopting shard 0's value", arr)
            report["replicated"] += 1
            return jnp.asarray(
                np.repeat(arr[:1], new_world, axis=0))
        return x  # replicated leaf: untouched

    new_inner = jax.tree.map(fix_leaf, inner)
    if residuals is not None:
        return _ZeroEFState(residuals, new_inner), report
    return new_inner, report


def _count_zeroed_residual() -> None:
    try:
        from ..timeline import metrics as _metrics
        _metrics.registry().counter(
            "horovod_ef_residual_zeroed_total",
            "EF residual buckets dropped (zeroed) during an elastic "
            "resize because shapes were irreconcilable").inc()
    except Exception:
        pass
