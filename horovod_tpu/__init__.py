"""horovod_tpu: a TPU-native data-parallel training framework.

A from-scratch rebuild of the capabilities of the reference system
(``agileml/horovod`` -- see SURVEY.md): the NCCL/MPI collective op layer is
re-implemented over XLA collectives on the ICI/DCN device mesh, the tensor
fusion buffer is an HBM-resident bucketing pass at trace time, the response
cache is a compiled-executable cache, and the background coordinator thread
disappears entirely under SPMD.

Public API (mirrors ``import horovod.torch as hvd`` surface)::

    import horovod_tpu as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(optax.adamw(1e-3),
                                   compression=hvd.Compression.bf16)
    step = hvd.make_train_step(loss_fn, opt)
"""

from .core import compat as _compat  # noqa: F401  (jax version shims)
from .core.basics import (  # noqa: F401
    init, shutdown, is_initialized, mesh, reduce_axes,
    size, rank, local_size, local_rank, cross_size, cross_rank,
    is_homogeneous, nccl_built, mpi_built, gloo_built, tpu_built,
    cuda_built, rocm_built, start_timeline, stop_timeline,
    mpi_threads_supported,
)
from .core.exceptions import (  # noqa: F401
    HorovodTpuError, HorovodInternalError, HostsUpdatedInterrupt,
    DesyncError, NotInitializedError, ProcessSetError,
)
from .core.desync import check_desync  # noqa: F401
from .core.process_sets import (  # noqa: F401
    ProcessSet, add_process_set, remove_process_set, get_process_set,
    process_set_names,
)
from .collectives.reduce_op import (  # noqa: F401
    ReduceOp, Average, Sum, Min, Max, Product, Adasum,
)
from .collectives.compression import Compression  # noqa: F401
from .collectives import ops as collective_ops  # noqa: F401  (in-step)
from . import ops  # noqa: F401  (pallas kernels: hvd.ops.flash_attention)
from .collectives.eager import (  # noqa: F401
    allreduce, allreduce_async, grouped_allreduce, grouped_allgather,
    grouped_reducescatter, allgather, allgatherv, broadcast, reducescatter,
    alltoall, alltoallv, barrier, join, synchronize, poll, local_result,
    replicated_stack, local_rank_count,
)
from .optim.distributed import (  # noqa: F401
    DistributedOptimizer, DistributedAdasumOptimizer, allreduce_gradients,
)
from .optim.zero import (  # noqa: F401  (ZeRO-1 sharded optimizer state)
    zero_init, zero_sharding, shard_zero_state, zero_report,
)
from .optim.functions import (  # noqa: F401
    allgather_object, broadcast_parameters, broadcast_optimizer_state,
    broadcast_object,
)
from . import elastic  # noqa: F401
from .utils.checkpoint import (  # noqa: F401
    save_checkpoint, restore_checkpoint, latest_checkpoint, checkpoint_path,
    save_checkpoint_sharded, restore_checkpoint_sharded,
)
from .training import (  # noqa: F401
    make_train_step, make_flax_train_step, make_eval_step, shard_batch,
    shard_batch_from_local, replicate, batch_sharding,
    replicated_sharding, sync_batch_norm,
    make_train_loop, make_flax_train_loop, stack_steps, shard_steps,
    stacked_batch_sharding, steps_per_execution, microbatches,
    mirror_opt_state_specs,
)
from .data import DevicePrefetcher, prefetch_to_device  # noqa: F401
from . import serving  # noqa: F401  (continuous-batching inference)
from .timeline.metrics import (  # noqa: F401  (unified metrics plane)
    StepReport, metrics_snapshot, last_step_report, render_prometheus,
)

__version__ = "0.1.0"
