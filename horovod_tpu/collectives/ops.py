"""In-step collective ops: the XLA-collective re-implementation of the
reference's op layer.

This is the TPU-native replacement for ``horovod/common/ops/nccl_operations.cc``
(``NCCLAllreduce``, ``NCCLAllgather``, ``NCCLBroadcast``, ``NCCLAlltoall``,
``NCCLReducescatter``) and ``mpi_operations.cc``: every collective is a
``jax.lax`` primitive emitted *inside* a ``jax.shard_map``-traced program
over the ICI/DCN mesh, so XLA schedules the DMA over the physical links --
there is no user-level comm library, no streams, no fusion-buffer memcpy
kernels.  Pre/post-scaling (the reference's CUDA ``ScaleBuffer`` kernels)
become fused elementwise multiplies.

All functions here must be called inside a traced context that binds the
mesh axis names (``shard_map`` over ``hvd.mesh()``); the eager wrappers in
``horovod_tpu.collectives.eager`` do that wrapping for host-level use.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .reduce_op import ReduceOp, Average, Sum, Min, Max, Product, Adasum
from ..core.state import global_state
from ..core import process_sets as _ps

AxisSpec = Union[str, Tuple[str, ...]]


def _default_axes() -> Tuple[str, ...]:
    st = global_state()
    if st.mesh is None:
        raise RuntimeError("horovod_tpu.init() must run before collectives")
    return tuple(st.mesh.axis_names)


def _resolve(axes: Optional[AxisSpec],
             process_set=None) -> Tuple[Tuple[str, ...], Optional[Tuple[int, ...]]]:
    """Resolve (axis names, member ranks) for a collective.

    ``members`` is ``None`` for the global set.  In-step process-set
    collectives are implemented with *masked* full-mesh collectives
    (non-members contribute the op's identity and keep their own value):
    JAX 0.9's shard_map does not lower ``axis_index_groups``, and on the
    ICI torus a full-ring reduction is usually as fast as a subgroup one
    anyway -- the masking costs one fused elementwise select.
    """
    if axes is None:
        axes = _default_axes()
    elif isinstance(axes, str):
        axes = (axes,)
    members = None
    if process_set is not None:
        ps = _ps.get_process_set(process_set)
        if not ps.is_global():
            members = ps.ranks
    return tuple(axes), members


def _member_mask(axes: Tuple[str, ...], members: Tuple[int, ...]):
    return jnp.isin(axis_index(axes), jnp.asarray(members))


def _member_pos(axes: Tuple[str, ...], members: Tuple[int, ...]):
    """This device's position within ``members`` (0 for non-members).

    ``members`` is static, so the rank->position table is baked into the
    program as a constant gather.
    """
    size = math.prod(lax.axis_size(a) for a in axes)
    table = np.zeros((size,), np.int32)
    table[list(members)] = np.arange(len(members), dtype=np.int32)
    return jnp.asarray(table)[axis_index(axes)]


def _gather_rows(x, axes: Tuple[str, ...]):
    """Stack every mesh member's ``x`` along a new leading axis, ordered by
    the row-major flattened index (matching :func:`axis_index`)."""
    g = x[None]
    for a in reversed(axes):
        g = lax.all_gather(g, a, axis=0, tiled=True)
    return g


def axis_size(axes: Optional[AxisSpec] = None) -> int:
    axes, _ = _resolve(axes)
    return math.prod(lax.axis_size(a) for a in axes)


def axis_index(axes: Optional[AxisSpec] = None):
    """Flattened device index along the reduce axes (row-major)."""
    axes, _ = _resolve(axes)
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def _divide_in_dtype(y, n: int):
    """Average's division, in the tensor's own dtype.

    Integer tensors use lax.div (C-style truncation toward zero -- the
    reference reduces in the tensor's dtype); true division would promote
    to float and change the output dtype.  // is NOT equivalent: it
    floors, so negative sums would round away from zero.
    """
    if jnp.issubdtype(y.dtype, jnp.integer):
        return lax.div(y, jnp.asarray(n, dtype=y.dtype))
    return y / jnp.asarray(n, dtype=y.dtype)


def allreduce(x,
              op: ReduceOp = Average,
              *,
              axes: Optional[AxisSpec] = None,
              process_set=None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              wire_codec=None):
    """Allreduce one array across the mesh (NCCLAllreduce analogue).

    With a process set, members reduce among themselves and non-members
    receive their input unchanged (they would not have called the op in
    the reference's per-rank model).

    ``wire_codec="fp8"`` (Adasum only): quantize the VHDD exchanges to
    e4m3 on the wire -- see ``adasum/xla.py``.  Sum/Average fp8 goes
    through :func:`fp8_allreduce` instead (a psum cannot carry it).
    """
    if wire_codec is not None and op is not Adasum:
        raise ValueError(
            f"wire_codec={wire_codec!r} applies to Adasum only; use "
            f"fp8_allreduce for {op}")
    axes, members = _resolve(axes, process_set)
    x_orig = x
    mask = None
    if members is not None:
        mask = _member_mask(axes, members)
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)

    if op in (Sum, Average):
        contrib = x if mask is None else jnp.where(mask, x,
                                                   jnp.zeros((), x.dtype))
        y = lax.psum(contrib, axes)
        if op is Average:
            n = len(members) if members is not None else \
                math.prod(lax.axis_size(a) for a in axes)
            y = _divide_in_dtype(y, n)
    elif op in (Min, Max):
        if mask is not None:
            if jnp.issubdtype(x.dtype, jnp.integer):
                info = jnp.iinfo(x.dtype)
                ident = info.max if op is Min else info.min
            else:
                ident = jnp.inf if op is Min else -jnp.inf
            x = jnp.where(mask, x, jnp.asarray(ident, x.dtype))
        y = lax.pmin(x, axes) if op is Min else lax.pmax(x, axes)
    elif op is Product:
        # No pprod primitive: gather then reduce (small tensors only; XLA
        # fuses the reduction with the gather output).
        if mask is not None:
            x = jnp.where(mask, x, jnp.ones((), x.dtype))
        g = lax.all_gather(x, axes, axis=0)
        # dtype= keeps the input dtype: jnp.prod would promote small ints
        # to a 32-bit accumulator (reference collectives reduce in the
        # tensor's own dtype, wraparound included).
        y = jnp.prod(g, axis=0, dtype=g.dtype)
    elif op is Adasum:
        from ..adasum.xla import (adasum_allreduce,
                                  adasum_allreduce_hierarchical,
                                  adasum_local_tree)
        if members is not None:
            if len(members) & (len(members) - 1) != 0:
                raise ValueError(
                    f"Adasum requires a power-of-two member count, got "
                    f"{len(members)}")
            if len(axes) == 1:
                # Masked VHDD over the full flat mesh: the same
                # vector-halving schedule paired by member POSITION, so
                # subset Adasum moves O(n) bytes per member like the
                # global path (was: gather O(mesh * n) everywhere + a
                # replicated local tree).
                y = adasum_allreduce(x, axis=axes[0], members=members,
                                     wire_codec=wire_codec)
            else:
                # Hierarchical (multi-axis) mesh: ppermute needs a flat
                # axis, so the subset falls back to gather + replicated
                # binary tree -- O(mesh * n) bytes, fine for the small
                # sets this path serves.
                if wire_codec is not None:
                    raise NotImplementedError(
                        "fp8 wire is not supported for process-set Adasum "
                        "on multi-axis meshes (the gather fallback has no "
                        "quantized exchange)")
                sel = _gather_rows(x, axes)[np.asarray(members)]
                y = adasum_local_tree([sel[i]
                                       for i in range(len(members))])
        elif len(axes) == 1:
            y = adasum_allreduce(x, axis=axes[0], wire_codec=wire_codec)
        elif len(axes) == 2:
            # Hierarchical (dcn, ici) mesh: the reference's hybrid Adasum
            # (intra-node ReduceScatter -> cross-node Adasum -> Allgather,
            # adasum_gpu_operations.cc).
            y = adasum_allreduce_hierarchical(x, dcn_axis=axes[0],
                                              ici_axis=axes[1],
                                              wire_codec=wire_codec)
        else:
            raise NotImplementedError(
                "Adasum supports flat or 2-level (dcn, ici) meshes")
    else:
        raise ValueError(f"unknown reduce op {op}")
    if postscale_factor != 1.0:
        y = y * jnp.asarray(postscale_factor, dtype=y.dtype)
    if mask is not None:
        y = jnp.where(mask, y, x_orig)
    return y


def hierarchical_allreduce(x,
                           op: ReduceOp = Average,
                           *,
                           dcn_axis: str,
                           ici_axis: str,
                           dcn_codec=None,
                           ici_codec=None,
                           dcn_residual=None,
                           prescale_factor: float = 1.0,
                           postscale_factor: float = 1.0):
    """Explicit two-level allreduce on a ``(dcn, ici)`` mesh
    (HOROVOD_HIERARCHICAL_ALLREDUCE parity, ``NCCLHierarchicalAllreduce``):
    intra-slice reduce-scatter over ICI, cross-slice allreduce of the
    1/n_ici shard over DCN, intra-slice allgather.

    A plain ``psum`` over both axes leaves the schedule to XLA (usually
    right on ICI-only meshes); this explicit form moves only the shard
    over the slow DCN links -- the reference's hierarchical algorithm --
    and is what the autotuner's ``hierarchical`` knob selects.  Sum and
    Average only (min/max/product don't scatter).

    Codecs apply PER LEG.  ``ici_codec`` (none/fp16/bf16 cast codecs
    only) sets the wire dtype of the intra-slice reduce-scatter and
    allgather; ``dcn_codec`` touches only the cross-slice hop of the
    1/n_ici shard and may additionally be fp8 (quantized gather-sum, f32
    accumulation) or an error-feedback codec (powersgd/topk over the DCN
    axis).  With an EF ``dcn_codec`` the return is
    ``(out, new_dcn_residual)`` -- ``dcn_residual`` is the previous
    step's unsent shard-domain f32 mass (``None`` = zeros), exactly the
    :func:`powersgd_allreduce` contract scoped to the DCN leg.

    The flat bucket is zero-padded to a multiple of
    ``microbatch_pad_quantum(n_ici)`` so the per-leg wire payload is
    mesh-invariant across every ``n_ici`` dividing 256 (what the scaling
    bench gates on).  When the DCN axis has extent 1 (single slice) the
    two-level decomposition would only add reduction-order noise, so the
    op statically falls back to the flat ``psum`` over both axes --
    bitwise identical to :func:`allreduce` on the same mesh.
    """
    from .compression import (Compression, fp8_quantize, is_error_feedback,
                              is_fp8, is_powersgd, is_topk)
    if op not in (Sum, Average):
        raise ValueError(
            f"hierarchical_allreduce supports Sum/Average, got {op}")
    ici_codec = ici_codec or Compression.none
    dcn_codec = dcn_codec or Compression.none
    if getattr(ici_codec, "wire_format", ""):
        raise ValueError(
            f"ICI leg codec must be psum-compatible (none|fp16|bf16), "
            f"got {ici_codec.__name__}")
    n_ici = lax.axis_size(ici_axis)
    n_dcn = lax.axis_size(dcn_axis)
    n = n_ici * n_dcn
    ef = is_error_feedback(dcn_codec)
    floating = jnp.issubdtype(x.dtype, jnp.floating)
    if not floating:
        # Non-float buckets ride uncompressed (both legs).
        ici_codec = Compression.none
        dcn_codec = Compression.none
    quantum = microbatch_pad_quantum(n_ici)
    shard_len = (x.size + (-x.size) % quantum) // n_ici

    if n_dcn == 1:
        # Single slice: the DCN hop is an identity; the flat psum is both
        # cheaper and bitwise identical to allreduce() on this mesh.
        y = allreduce(x, op, axes=(dcn_axis, ici_axis),
                      prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor)
        if ef:
            res = dcn_residual if dcn_residual is not None else \
                jnp.zeros((shard_len,), jnp.float32)
            return y, res
        return y

    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    shape, dtype = x.shape, x.dtype
    flat = x.ravel()
    pad = (-flat.size) % quantum
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    padded = flat.size
    itemsize = jnp.dtype(dtype).itemsize
    ici_wire, ici_ctx = ici_codec.compress(flat)
    ici_itemsize = jnp.dtype(ici_wire.dtype).itemsize
    # Trace-time per-leg registration (fires once per trace): the legs
    # come from the shared exchange-plan IR -- the SAME plan object the
    # auditor and explain_plan consume -- and each row carries the wire
    # byte accounting (RS/AG move the full padded bucket at the ICI wire
    # width, the DCN hop only the 1/n_ici shard at the DCN codec's
    # payload).
    from ..controller import fusion as _fusion
    from ..timeline import spans as _spans
    for _leg in _fusion.plan_exchange(
            "hier", size=int(x.size), dtype=str(dtype),
            n_dcn=int(n_dcn), n_ici=int(n_ici),
            ici_codec=ici_codec, dcn_codec=dcn_codec,
            dcn_axis=dcn_axis, ici_axis=ici_axis).legs:
        _spans.note_leg(_leg)

    shard = lax.psum_scatter(ici_wire, ici_axis, scatter_dimension=0,
                             tiled=True)
    shard = ici_codec.decompress(shard, ici_ctx)

    new_residual = None
    if ef and floating:
        # Compressed leader exchange: powersgd/topk of the shard over the
        # DCN axis only; the residual lives in the shard domain.
        if is_powersgd(dcn_codec):
            shard, new_residual = powersgd_allreduce(
                shard, Sum, rank=dcn_codec.rank, axes=(dcn_axis,),
                residual=dcn_residual, note=False)
        else:
            shard, new_residual = topk_allreduce(
                shard, Sum, fraction=dcn_codec.fraction, axes=(dcn_axis,),
                residual=dcn_residual, note=False)
    elif is_fp8(dcn_codec):
        # Quantized gather-sum: e4m3 on the DCN wire, exact f32
        # accumulation on chip (a psum would reduce IN fp8).
        q, scale = fp8_quantize(shard.astype(jnp.float32))
        gq = lax.all_gather(q[None], dcn_axis, axis=0, tiled=True)
        gs = lax.all_gather(scale.reshape(1), dcn_axis, axis=0,
                            tiled=True)
        shard = jnp.sum(gq.reshape(n_dcn, -1).astype(jnp.float32)
                        * gs[:, None], axis=0).astype(dtype)
    else:
        dcn_wire, dcn_ctx = dcn_codec.compress(shard)
        dcn_wire = lax.psum(dcn_wire, dcn_axis)
        shard = dcn_codec.decompress(dcn_wire, dcn_ctx)
    if op is Average:
        shard = _divide_in_dtype(shard, n)
    ag_wire, ag_ctx = ici_codec.compress(shard)
    y = lax.all_gather(ag_wire, ici_axis, axis=0, tiled=True)
    y = ici_codec.decompress(y, ag_ctx)
    if pad:
        y = y[:-pad]
    y = y.reshape(shape)
    if postscale_factor != 1.0:
        y = y * jnp.asarray(postscale_factor, dtype=y.dtype)
    if ef:
        if new_residual is None:  # non-float bucket: nothing was unsent
            new_residual = dcn_residual if dcn_residual is not None else \
                jnp.zeros((shard_len,), jnp.float32)
        return y, new_residual
    return y


def chunked_allreduce(x,
                      op: ReduceOp = Average,
                      *,
                      chunk_bytes: int,
                      axes: Optional[AxisSpec] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0):
    """Allreduce decomposed into chunk-sized reduce-scatter + all-gather
    pairs (``HOROVOD_EXCHANGE_CHUNK_MB``; Sum/Average, full mesh only).

    This XLA toolchain emits all-gather (and collective-permute) with async
    start/done pairs but keeps all-reduce and reduce-scatter synchronous
    (see ``utils/scaling.py``), so one monolithic bucket allreduce gives the
    latency-hiding scheduler nothing to overlap.  Splitting the bucket into
    chunk-sized ``psum_scatter`` + ``all_gather`` pieces moves the same
    total link payload -- RS(B) + AG(B) == 2*(n-1)/n*B == AR(B) -- while
    handing the scheduler independent pieces to interleave with the
    remaining backward compute.  Each chunk is zero-padded to a multiple of
    the mesh size (at most ``n-1`` elements per chunk, same trick as
    :func:`hierarchical_allreduce`).

    The reduction ORDER differs from a single ``psum`` (scatter-reduce
    semantics), so results are close but not bitwise identical to
    :func:`allreduce`; the knob is therefore opt-in (0 = off).
    """
    if op not in (Sum, Average):
        raise ValueError(f"chunked_allreduce supports Sum/Average, got {op}")
    axes, members = _resolve(axes, None)
    n = math.prod(lax.axis_size(a) for a in axes)
    if n == 1 or int(chunk_bytes) <= 0:
        return allreduce(x, op, axes=axes, prescale_factor=prescale_factor,
                         postscale_factor=postscale_factor)
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    shape, dtype = x.shape, x.dtype
    flat = x.ravel()
    itemsize = jnp.dtype(dtype).itemsize
    # A chunk holds chunk_bytes, rounded up to a multiple of n elements so
    # every chunk scatters evenly across the mesh.
    chunk_elems = max(1, int(chunk_bytes) // itemsize)
    chunk_elems += (-chunk_elems) % n
    # Trace-time leg registration for straggler attribution (fires once
    # per trace; RS(B)+AG(B) moves an equivalent-allreduce payload).
    # The leg row comes from the shared plan IR: chunking acts on the
    # already-compressed wire buffer, so the plan sees the wire dtype.
    from ..controller import fusion as _fusion
    from ..timeline import spans as _spans
    _spans.note_leg(_fusion.plan_exchange(
        "chunked", size=int(flat.size), dtype=str(dtype),
        chunk_bytes=int(chunk_bytes), world=int(n)).legs[0])
    pieces = []
    for off in range(0, flat.size, chunk_elems):
        piece = flat[off:off + chunk_elems]
        pad = (-piece.size) % n
        if pad:
            piece = jnp.concatenate([piece, jnp.zeros((pad,), dtype)])
        shard = lax.psum_scatter(piece, axes, scatter_dimension=0,
                                 tiled=True)
        if op is Average:
            shard = _divide_in_dtype(shard, n)
        full = lax.all_gather(shard, axes, axis=0, tiled=True)
        if pad:
            full = full[:-pad]
        pieces.append(full)
    y = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
    y = y.reshape(shape)
    if postscale_factor != 1.0:
        y = y * jnp.asarray(postscale_factor, dtype=y.dtype)
    return y


def microbatch_pad_quantum(n: int, base: int = 256) -> int:
    """Padding quantum for the microbatched exchange: ``lcm(n, base)``.

    Buckets are zero-padded to a multiple of this before the per-microbatch
    reduce-scatter.  Padding to a multiple of ``n`` alone would make the
    padded byte count (and hence the wire payload the scaling bench gates
    on) depend on the mesh size; padding to ``lcm(n, base)`` keeps it
    mesh-invariant across every ``n`` dividing ``base`` (256 covers the
    v5e/v5p pod sizes the bench sweeps), so payload == planner holds at
    the same 3e-7 spread as the zero1/chunked cases.
    """
    return base * n // math.gcd(base, n)


def psum_scatter_bucket(flat, *, axes: Tuple[str, ...], quantum: int):
    """Zero-pad ``flat`` to a multiple of ``quantum`` and reduce-scatter
    it (Sum) over ``axes``; returns this rank's ``padded/n`` shard.

    The building block of the backward-overlap exchange: each microbatch's
    gradient bucket goes on the wire as one tiled ``psum_scatter`` the
    moment its backward segment produces it, while later microbatches are
    still computing.  The caller accumulates shards across microbatches and
    closes with one :func:`allgather_bucket`.
    """
    pad = (-flat.size) % quantum
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return lax.psum_scatter(flat, axes, scatter_dimension=0, tiled=True)


def allgather_bucket(shard, size: int, *, axes: Tuple[str, ...]):
    """All-gather a :func:`psum_scatter_bucket` shard back to the full
    bucket and strip the padding down to ``size`` elements."""
    full = lax.all_gather(shard, axes, axis=0, tiled=True)
    return full[:size] if full.size != size else full


def grouped_allreduce(xs: Sequence,
                      op: ReduceOp = Average,
                      *,
                      axes: Optional[AxisSpec] = None,
                      process_set=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0):
    """Allreduce a list of arrays as one fused unit (GroupTable analogue).

    The arrays are flattened into a single buffer (the HBM-resident
    fusion-buffer analogue -- reference ``fusion_buffer_manager.cc``), one
    collective is emitted, and the results are split back out.  Mixed dtypes
    are grouped per dtype.
    """
    from ..controller.fusion import fuse_flat, unfuse_flat
    xs = list(xs)
    if not xs:
        return []
    fused, spec = fuse_flat(xs)
    reduced = [
        allreduce(buf, op, axes=axes, process_set=process_set,
                  prescale_factor=prescale_factor,
                  postscale_factor=postscale_factor)
        for buf in fused
    ]
    return unfuse_flat(reduced, spec)


def allgather(x,
              *,
              axes: Optional[AxisSpec] = None,
              process_set=None,
              axis: int = 0,
              tiled: bool = True):
    """Concatenate each worker's array along ``axis`` (NCCLAllgather).

    Like the reference, workers may differ only in dimension ``axis`` --
    but XLA requires static equal shapes, so unequal first dims must go
    through :func:`allgatherv` (padding-based) instead.

    With a process set, every device (SPMD traces one program) computes the
    gather of the MEMBER values -- shape ``[len(set) * d_axis, ...]`` when
    tiled.  Non-members receive the member gather too (in the reference's
    per-rank model they would never have called the op).
    """
    axes, members = _resolve(axes, process_set)
    if members is not None:
        sel = _gather_rows(x, axes)[np.asarray(members)]  # [m, ...]
        if tiled:
            return jnp.concatenate([sel[i] for i in range(len(members))],
                                   axis=axis)
        return jnp.moveaxis(sel, 0, axis)
    y = x
    for a in reversed(axes):
        y = lax.all_gather(y, a, axis=axis, tiled=tiled)
    return y


def broadcast(x,
              root_rank: int = 0,
              *,
              axes: Optional[AxisSpec] = None,
              process_set=None):
    """Every worker receives root's value (NCCLBroadcast analogue).

    Implemented as a masked psum: ``sum_i (i == root ? x_i : 0)``.  XLA
    lowers this to the same ring traffic a broadcast would use, and it
    composes with axis_index_groups for process sets.
    """
    axes, members = _resolve(axes, process_set)
    idx = axis_index(axes)
    member_mask = None
    if members is not None:
        # root_rank is a *global* rank; it must be a member of the set.
        if root_rank not in members:
            raise ValueError(
                f"broadcast root_rank {root_rank} is not a member of the "
                f"process set (ranks {tuple(members)})")
        # Non-members keep their own value (identity).
        member_mask = _member_mask(axes, members)
    mask = (idx == root_rank)
    if jnp.issubdtype(x.dtype, jnp.bool_):
        xi = jnp.where(mask, x, False).astype(jnp.int8)
        out = lax.psum(xi, axes).astype(jnp.bool_)
    else:
        masked = jnp.where(mask, x, jnp.zeros((), x.dtype))
        out = lax.psum(masked, axes)
    if member_mask is not None:
        out = jnp.where(member_mask, out, x)
    return out


def reducescatter(x,
                  op: ReduceOp = Average,
                  *,
                  axes: Optional[AxisSpec] = None,
                  process_set=None,
                  scatter_axis: int = 0):
    """Reduce then scatter shards along ``scatter_axis`` (NCCLReducescatter).

    With a process set, members reduce among themselves (masked full-mesh
    psum, or the masked allreduce for min/max/product) and each member
    takes the shard at its position within the set;
    ``x.shape[scatter_axis]`` must divide by the set size.  Non-members
    receive an UNSPECIFIED value (shard 0 of the member reduction on the
    sum path, their own shard 0 on the min/max/product path -- in the
    reference's per-rank model a non-member never calls the op).
    """
    axes, members = _resolve(axes, process_set)
    if op is Adasum:
        raise NotImplementedError(
            "reducescatter does not support Adasum (the reference's Adasum "
            "is an allreduce-shaped op); use allreduce(op=Adasum)")
    if op not in (Sum, Average, Min, Max, Product):
        raise ValueError(f"unknown reduce op {op}")
    if members is not None:
        m = len(members)
        d = x.shape[scatter_axis]
        if d % m:
            raise ValueError(
                f"reducescatter over a {m}-member process set needs "
                f"dim {scatter_axis} divisible by {m}, got {d}")
        if op in (Min, Max, Product):
            y = allreduce(x, op, axes=axes, process_set=process_set)
        else:
            mask = _member_mask(axes, members)
            contrib = jnp.where(mask, x, jnp.zeros((), x.dtype))
            y = lax.psum(contrib, axes)
            if op is Average:
                y = _divide_in_dtype(y, m)
        shard = d // m
        pos = _member_pos(axes, members)
        return lax.dynamic_slice_in_dim(y, pos * shard, shard, scatter_axis)
    if op in (Min, Max, Product):
        # No min/max/prod scatter primitive: reduce the full vector
        # (pmin/pmax, or the gathered product the allreduce path uses)
        # and take this rank's shard.  Bytes are O(n) like an allreduce
        # rather than the ring-scatter's O(n/p) -- matching the
        # reference, whose NCCL reducescatter supports these ops and is
        # the parity point.
        n = math.prod(lax.axis_size(a) for a in axes)
        d = x.shape[scatter_axis]
        if d % n:
            raise ValueError(
                f"reducescatter needs dim {scatter_axis} divisible by the "
                f"mesh size {n}, got {d}")
        y = allreduce(x, op, axes=axes)
        return lax.dynamic_slice_in_dim(
            y, axis_index(axes) * (d // n), d // n, scatter_axis)
    y = x
    for a in axes:
        y = lax.psum_scatter(y, a, scatter_dimension=scatter_axis, tiled=True)
    if op is Average:
        n = math.prod(lax.axis_size(a) for a in axes)
        y = _divide_in_dtype(y, n)
    return y


def alltoall(x,
             *,
             axes: Optional[AxisSpec] = None,
             process_set=None,
             split_axis: int = 0,
             concat_axis: int = 0):
    """Exchange equal splits with every worker (NCCLAlltoall analogue).

    The reference supports uneven ``splits``; XLA's static shapes require
    equal splits -- uneven exchange is provided by ``alltoallv`` (padded).
    This is the expert-parallel / Ulysses building block (SURVEY.md 5.7).

    With a process set, members exchange their ``len(set)`` splits through
    a masked full-mesh alltoall (non-member slots carry zeros; non-members
    receive zeros).  ``x.shape[split_axis]`` must divide by the set size.

    Works on flat AND hierarchical meshes: a multi-axis exchange uses the
    row-major flattened rank order (matching :func:`axis_index`).
    """
    axes, members = _resolve(axes, process_set)
    a = axes[0] if len(axes) == 1 else axes
    if members is None:
        return lax.all_to_all(x, a, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
    m = len(members)
    size = math.prod(lax.axis_size(ax) for ax in axes)
    d = x.shape[split_axis]
    if d % m:
        raise ValueError(
            f"alltoall over a {m}-member process set needs dim "
            f"{split_axis} divisible by {m}, got {d}")
    chunk = d // m
    # [m, chunk, rest...]: split i is this member's payload for member i.
    xs = jnp.moveaxis(x, split_axis, 0).reshape(
        (m, chunk) + tuple(np.delete(np.array(x.shape), split_axis)))
    send = jnp.zeros((size,) + xs.shape[1:], x.dtype)
    send = send.at[np.asarray(members)].set(xs)
    recv = lax.all_to_all(send, a, split_axis=0, concat_axis=0, tiled=True)
    sel = recv[np.asarray(members)]          # [m, chunk, rest...]
    # Match the global tiled semantics: split_axis shrinks to ``chunk``,
    # concat_axis grows by ``m``.
    pieces = jnp.moveaxis(sel, 1, split_axis + 1)   # [m] + x-like shape
    return jnp.concatenate([pieces[i] for i in range(m)], axis=concat_axis)


def alltoallv(x, send_counts, *, axes: Optional[AxisSpec] = None,
              process_set=None, max_count: int,
              return_overflow: bool = False,
              strict: Optional[bool] = None):
    """Uneven alltoall (padded alltoallv; NCCLAlltoall with ``splits``).

    The reference exchanges ragged splits directly (its negotiation shares
    the counts); XLA needs static shapes, so each split is padded to the
    static bound ``max_count`` and receivers get the valid lengths
    alongside.  ``send_counts`` may be a traced per-device value -- the
    padding/masking is dynamic-slice based, so routing decisions computed
    inside the step (e.g. MoE dispatch) stay on device.

    Args:
      x: ``[total, ...]`` local rows; the split for peer ``i`` occupies
        rows ``[sum(send_counts[:i]), sum(send_counts[:i+1]))`` (rank-order
        concatenation, the reference's layout).
      send_counts: int array ``[size]``; ``send_counts[i]`` rows go to
        global rank ``i``.
      max_count: static upper bound on any single split.  A traced count
        exceeding it is truncated: only the first ``max_count`` rows of
        that split transfer and the receiver's count reports the clamped
        value (size your bound for the worst case, like an MoE capacity
        factor).  The reference ERRORS on inconsistent splits and never
        drops rows; request ``return_overflow=True`` to detect truncation
        (dropped tokens in an MoE exchange are otherwise invisible).
      return_overflow: also return the per-sender count of rows DROPPED by
        clamping.  Costs nothing extra: the original counts ride the same
        counts collective as the clamped ones.
      strict: loud mode (default: the ``HOROVOD_ALLTOALLV_STRICT`` env
        var).  Emits a ``jax.experimental.checkify.check`` that fails the
        step when ANY row is dropped, reporting the per-sender dropped
        counts -- the reference errors on inconsistent splits and never
        silently drops rows; this is the TPU-compiled equivalent (the axon
        backend has no host callbacks, so the error is functionalized).
        The enclosing jit/shard_map step must be wrapped in
        ``checkify.checkify(...)`` and the returned error thrown
        (``err.throw()``); an unwrapped strict step fails at TRACE time
        with checkify's "not functionalized" error, which is still loud,
        never silent.  Uses the same already-computed overflow counts as
        ``return_overflow`` -- zero extra communication.  The env var is
        read at TRACE time: set it before the step is first traced --
        executables already compiled with strict off stay off (jit cache
        keys do not include the environment).

    Returns:
      ``(recv, recv_counts)``: ``recv[j]`` is ``[max_count, ...]`` holding
      the split received from rank ``j`` (zero-padded past
      ``recv_counts[j]``); ``recv_counts`` is ``[size]``, every entry
      ``<= max_count``.  With ``return_overflow=True``, a third element
      ``overflow`` ([size] int32): ``overflow[j]`` rows addressed to this
      device by rank ``j`` were dropped (0 everywhere means the exchange
      was lossless).

    With a process set, ``send_counts`` is indexed by SET position (one
    count per member, splits concatenated in member order) and the
    results cover members only: ``recv`` is ``[len(set), max_count, ...]``
    and ``recv_counts``/``overflow`` are ``[len(set)]``.  Non-member
    devices exchange nothing (their results are all-zero).
    """
    axes, members = _resolve(axes, process_set)
    if members is not None:
        # Subset ragged exchange over the full mesh: member counts
        # (indexed by SET position) scatter into global slots, non-member
        # devices' counts are masked to zero (they send nothing and, by
        # construction, receive zero rows from every member).
        m = len(members)
        send_counts = jnp.asarray(send_counts, jnp.int32)
        if send_counts.shape != (m,):
            raise ValueError(
                f"send_counts must have shape ({m},) (one count per set "
                f"member), got {send_counts.shape}")
        size = math.prod(lax.axis_size(ax) for ax in axes)
        full = jnp.zeros((size,), jnp.int32).at[
            np.asarray(members)].set(send_counts)
        full = jnp.where(_member_mask(axes, members), full, 0)
        sel = np.asarray(members)
        out = alltoallv(x, full, axes=axes, max_count=max_count,
                        return_overflow=return_overflow, strict=strict)
        return tuple(o[sel] for o in out)
    a = axes[0] if len(axes) == 1 else axes
    size = math.prod(lax.axis_size(ax) for ax in axes)
    send_counts = jnp.asarray(send_counts, jnp.int32)
    if send_counts.shape != (size,):
        raise ValueError(
            f"send_counts must have shape ({size},) (one count per mesh "
            f"member), got {send_counts.shape}")
    # Offsets follow the caller's layout (the ORIGINAL counts); a split
    # larger than max_count is truncated to max_count rows, and the clamped
    # count is what the receiver sees -- overflow loses the tail but stays
    # internally consistent (recv_counts[j] <= max_count always), and is
    # reported via ``return_overflow``.
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(send_counts)[:-1]])
    clamped = jnp.minimum(send_counts, max_count)
    # Tail padding keeps every dynamic slice in bounds (XLA clamps
    # out-of-bounds starts, which would otherwise duplicate trailing rows).
    pad = jnp.zeros((max_count,) + x.shape[1:], x.dtype)
    xp = jnp.concatenate([x, pad], axis=0)
    pieces = jax.vmap(
        lambda off: lax.dynamic_slice_in_dim(xp, off, max_count, axis=0)
    )(offsets)                                # [size, max_count, ...]
    valid = (jnp.arange(max_count, dtype=jnp.int32)[None, :]
             < clamped[:, None])              # [size, max_count]
    valid = valid.reshape(valid.shape + (1,) * (x.ndim - 1))
    pieces = jnp.where(valid, pieces, jnp.zeros((), x.dtype))
    recv = lax.all_to_all(pieces, a, split_axis=0, concat_axis=0, tiled=True)
    # One counts collective carries BOTH the clamped and the original
    # counts ([size, 2] rows), so overflow detection is free.
    pair = lax.all_to_all(jnp.stack([clamped, send_counts], axis=1), a,
                          split_axis=0, concat_axis=0, tiled=True)
    recv_counts = pair[:, 0]
    if strict is None:
        from ..core.config import _env_bool
        strict = _env_bool("ALLTOALLV_STRICT")
    if strict:
        from jax.experimental import checkify
        overflow = pair[:, 1] - pair[:, 0]
        checkify.check(
            jnp.logical_not(jnp.any(overflow > 0)),
            "alltoallv dropped rows (HOROVOD_ALLTOALLV_STRICT): per-sender "
            "dropped counts {ov} at max_count=" + str(int(max_count))
            + " -- raise max_count or fix the split computation",
            ov=overflow)
    if return_overflow:
        return recv, recv_counts, pair[:, 1] - pair[:, 0]
    return recv, recv_counts


def fp8_allreduce(x,
                  op: ReduceOp = Average,
                  *,
                  axes: Optional[AxisSpec] = None,
                  prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0):
    """Allreduce with an e4m3 wire and f32 on-chip accumulation.

    ``Compression.fp8``'s exchange (see ``compression.py``): a plain psum
    would ACCUMULATE in the wire dtype (3 mantissa bits, overflow at 448),
    so the reduction is decomposed TPU-natively instead:

    1. shard the flat bucket ``n`` ways; quantize each destination row
       with its own max-abs scale (``n`` f32 scalars);
    2. ``all_to_all`` the fp8 rows (the scale matrix rides a tiny
       ``all_gather``);
    3. dequantize and reduce THIS rank's shard in f32;
    4. re-quantize the result shard and ``all_gather`` it back -- the one
       collective this toolchain emits ASYNC for (scaling.py round-4
       capability matrix), so the rebuild can hide behind compute.

    Wire cost: 2 * B/4 * (n-1)/n link bytes -- 4x less than fp32 psum,
    2x less than fp16.  Numerics: two e4m3 roundings end-to-end
    (~2^-4 relative each); the REDUCTION itself is exact f32, unlike
    what summing in any wire dtype would give.  Floating-point inputs
    only; process sets are not supported (no masked identity exists for
    a quantized exchange) -- use fp16/bf16 compression there.
    """
    axes, members = _resolve(axes)
    if members is not None:
        raise NotImplementedError(
            "fp8_allreduce does not support process sets; use fp16/bf16 "
            "compression for subset reductions")
    if op not in (Sum, Average):
        raise ValueError(f"fp8_allreduce supports Sum/Average, got {op}")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        raise ValueError(f"fp8 wire needs a floating dtype, got {x.dtype}")
    from .compression import fp8_quantize, fp8_dequantize

    a = axes[0] if len(axes) == 1 else axes
    n = math.prod(lax.axis_size(ax) for ax in axes)
    shape, dtype = x.shape, x.dtype
    x32 = x.astype(jnp.float32)
    if prescale_factor != 1.0:
        x32 = x32 * prescale_factor
    flat = x32.ravel()
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    rows = flat.reshape(n, -1)                     # row j -> rank j
    # Trace-time leg registration: fp8 all_to_all + result allgather,
    # one wire byte per e4m3 element in each direction (plan-IR row).
    from ..controller import fusion as _fusion
    from ..timeline import spans as _spans
    _spans.note_leg(_fusion.plan_exchange(
        "fp8", size=int(x.size), world=int(n)).legs[0])
    q, scales = fp8_quantize(rows, axis=0)         # per-destination scales
    recv = lax.all_to_all(q, a, split_axis=0, concat_axis=0, tiled=True)
    # scale matrix: S[src, dst]; my column is the scale each sender used
    # for the row now in ``recv[src]``.
    smat = _gather_rows(scales, axes)              # [n, n]
    my = axis_index(axes)
    my_scales = smat[:, my] if len(axes) > 1 else \
        jnp.take(smat, my, axis=1)
    acc = jnp.sum(recv.astype(jnp.float32) * my_scales[:, None], axis=0)
    if op is Average:
        acc = acc / n
    if postscale_factor != 1.0:
        acc = acc * postscale_factor
    qr, s2 = fp8_quantize(acc)
    gathered = _gather_rows(qr, axes)              # [n, chunk]
    s2_all = _gather_rows(s2, axes)                # [n]
    out = (gathered.astype(jnp.float32) * s2_all[:, None]).ravel()
    if pad:
        out = out[:-pad]
    return out.reshape(shape).astype(dtype)


def _powersgd_seed_matrix(cols: int, rank: int):
    """Deterministic, RNG-free right-factor init ``Q0`` of shape
    ``[cols, rank]``.

    Every rank must start the power iteration from the SAME Q0 (the P
    allreduce assumes it), and the eager join-replay path re-traces the
    exchange on drained ranks, so the init must be a pure function of the
    shape -- no PRNG key threading.  Incommensurate cosine phases give
    columns that are linearly independent in practice (orthogonalization
    downstream cleans up conditioning).
    """
    i = jnp.arange(cols, dtype=jnp.float32)[:, None]
    j = jnp.arange(rank, dtype=jnp.float32)[None, :]
    return jnp.cos(i * (j + 1.0) * 0.9182736 + (j + 1.0) * 0.3717)


def _orthonormalize_columns(p):
    """Modified Gram-Schmidt over the (few) columns of ``p`` -- the one
    orthogonalization round of the PowerSGD exchange.  Unrolled Python loop:
    rank is small and static, so XLA sees straight-line code."""
    cols = []
    for k in range(p.shape[1]):
        v = p[:, k]
        for u in cols:
            v = v - jnp.dot(u, v) * u
        norm = jnp.sqrt(jnp.sum(v * v))
        cols.append(v / jnp.maximum(norm, 1e-12))
    return jnp.stack(cols, axis=1)


def powersgd_allreduce(x,
                       op: ReduceOp = Average,
                       *,
                       rank: int,
                       axes: Optional[AxisSpec] = None,
                       residual=None,
                       prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0,
                       note: bool = True):
    """Rank-``rank`` PowerSGD allreduce (Vogels et al., 2019): low-rank
    factor exchange with f32 on-chip arithmetic.

    The flat bucket is matricized near-square (``m x c``, zero-padded);
    one power-iteration round runs THROUGH the collective:

    1. ``P = M @ Q0`` with a deterministic shared ``Q0`` -- allreduce
       (mean) the ``[m, r]`` left factor;
    2. orthonormalize ``P`` locally (identical on every rank: one
       Gram-Schmidt round, f32);
    3. ``Q = M^T @ P`` -- allreduce (mean) the ``[c, r]`` right factor;
    4. rebuild ``P @ Q^T ~= mean(M)`` (the projection of the mean gradient
       onto span(P)).

    Wire bytes: two allreduces of ``r * (m + c)`` f32 elements vs one of
    ``m * c`` -- for a B-element bucket the reduction factor is
    ``B / (2 r (m + c)) ~= sqrt(B) / (4 r)``.

    The approximation is biased, so callers that train through it must use
    error feedback: pass the previous step's ``residual`` (flat f32, same
    element count as ``x``) and the return is ``(out, new_residual)`` where
    ``new_residual = (x + residual) - P @ Q_local^T`` -- the part of THIS
    rank's contribution the averaged factors did not carry.  ``residual``
    of ``None`` means zeros (stateless use: autotune sampling, the eager
    path).  Floating inputs, Sum/Average, full mesh only (no masked
    identity exists for a factored exchange).
    """
    axes, members = _resolve(axes)
    if members is not None:
        raise NotImplementedError(
            "powersgd_allreduce does not support process sets; use "
            "fp16/bf16 compression for subset reductions")
    if op not in (Sum, Average):
        raise ValueError(f"powersgd_allreduce supports Sum/Average, got {op}")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        raise ValueError(
            f"powersgd wire needs a floating dtype, got {x.dtype}")
    from .compression import powersgd_matrix_shape

    n = math.prod(lax.axis_size(ax) for ax in axes)
    shape, dtype = x.shape, x.dtype
    size = x.size
    m, c = powersgd_matrix_shape(size)
    pad = m * c - size
    r = max(1, min(int(rank), m, c))
    if note:
        # Trace-time leg registration: two f32 factor allreduces
        # (plan-IR row).
        from ..controller import fusion as _fusion
        from ..timeline import spans as _spans
        _spans.note_leg(_fusion.plan_exchange(
            "powersgd", size=int(size), rank=int(rank)).legs[0])

    from ..ops import pallas as _pallas
    if _pallas.pallas_enabled("fused_update"):
        # Fused path (PR 13): the three HBM passes between the factor
        # psums run as Pallas kernels (ops.fused_update); the psums
        # themselves stay HERE in XLA, so the wire contract -- two f32
        # allreduces of r*m and r*c elements -- and the _EFState carry
        # are identical to the unfused path below.
        from ..ops import fused_update as _fused
        if note:
            from ..controller import fusion as _fusion
            from ..timeline import spans as _spans
            _spans.note_leg(_fusion.plan_exchange(
                "kernel", kernel="fused_update", nbytes=int(size) * 4
            ).legs[0])
        xf = x.ravel()
        xp = jnp.concatenate([xf, jnp.zeros((pad,), xf.dtype)]) \
            if pad else xf
        res_mat = None
        if residual is not None:
            rf = residual.astype(jnp.float32).ravel()
            rp = jnp.concatenate([rf, jnp.zeros((pad,), jnp.float32)]) \
                if pad else rf
            res_mat = rp.reshape(m, c)
        acc_mat, p_local = _fused.matricize_p(
            xp.reshape(m, c), res_mat, _powersgd_seed_matrix(c, r),
            prescale=prescale_factor)
        p = lax.psum(p_local, axes if len(axes) > 1 else axes[0]) / n
        p_orth, q_local = _fused.orthonormalize_q(acc_mat, p)
        q = lax.psum(q_local, axes if len(axes) > 1 else axes[0]) / n
        out_mat, res_out = _fused.reconstruct_residual(
            acc_mat, p_orth, q, q_local,
            n_scale=float(n) if op is Sum else 1.0,
            postscale=postscale_factor)
        return (out_mat.ravel()[:size].reshape(shape).astype(dtype),
                res_out.ravel()[:size])

    acc = x.astype(jnp.float32).ravel()
    if prescale_factor != 1.0:
        acc = acc * prescale_factor
    if residual is not None:
        acc = acc + residual.astype(jnp.float32).ravel()
    flat = jnp.concatenate([acc, jnp.zeros((pad,), jnp.float32)]) \
        if pad else acc
    mat = flat.reshape(m, c)

    p = mat @ _powersgd_seed_matrix(c, r)          # [m, r]
    p = lax.psum(p, axes if len(axes) > 1 else axes[0]) / n
    p = _orthonormalize_columns(p)
    q_local = mat.T @ p                            # [c, r]
    q = lax.psum(q_local, axes if len(axes) > 1 else axes[0]) / n

    approx = (p @ q.T).ravel()[:size]              # ~= mean over ranks
    own = (p @ q_local.T).ravel()[:size]           # this rank's share
    new_residual = acc - own
    out = approx * n if op is Sum else approx
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return out.reshape(shape).astype(dtype), new_residual


def topk_allreduce(x,
                   op: ReduceOp = Average,
                   *,
                   fraction: float,
                   axes: Optional[AxisSpec] = None,
                   residual=None,
                   prescale_factor: float = 1.0,
                   postscale_factor: float = 1.0,
                   note: bool = True):
    """Top-``fraction`` sparsified allreduce (DGC-style, Lin et al., 2018).

    Each rank keeps its ``k = ceil(fraction * size)`` largest-magnitude
    elements and allgathers ``(value f32, index int32)`` pairs; every rank
    scatter-adds all ``n * k`` pairs into a dense f32 bucket -- duplicate
    indices across ranks accumulate correctly, and the reduction is exact
    f32 over what was sent.  Wire bytes: ``8k`` per rank vs ``4 * size``
    (a ``1 / (2 * fraction)`` reduction before allgather-vs-allreduce
    link accounting).

    Error feedback mirrors :func:`powersgd_allreduce`: returns
    ``(out, new_residual)`` with ``new_residual = acc - own_sparse`` (the
    elements this rank did NOT send).  Floating inputs, Sum/Average, full
    mesh only.
    """
    axes, members = _resolve(axes)
    if members is not None:
        raise NotImplementedError(
            "topk_allreduce does not support process sets; use fp16/bf16 "
            "compression for subset reductions")
    if op not in (Sum, Average):
        raise ValueError(f"topk_allreduce supports Sum/Average, got {op}")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        raise ValueError(f"topk wire needs a floating dtype, got {x.dtype}")
    from .compression import topk_count

    n = math.prod(lax.axis_size(ax) for ax in axes)
    shape, dtype = x.shape, x.dtype
    acc = x.astype(jnp.float32).ravel()
    if prescale_factor != 1.0:
        acc = acc * prescale_factor
    if residual is not None:
        acc = acc + residual.astype(jnp.float32).ravel()
    size = acc.size
    k = min(topk_count(size, fraction), size)
    if note:
        # Trace-time leg registration: (value f32, index int32) pairs
        # (plan-IR row).
        from ..controller import fusion as _fusion
        from ..timeline import spans as _spans
        _spans.note_leg(_fusion.plan_exchange(
            "topk", size=int(size), fraction=float(fraction)).legs[0])

    _, idx = lax.top_k(jnp.abs(acc), k)            # int32 indices
    vals = jnp.take(acc, idx)
    gv = _gather_rows(vals, axes)                  # [n, k]
    gi = _gather_rows(idx, axes)                   # [n, k]
    dense = jnp.zeros((size,), jnp.float32).at[gi.ravel()].add(gv.ravel())
    if op is Average:
        dense = dense / n
    if postscale_factor != 1.0:
        dense = dense * postscale_factor
    own = jnp.zeros((size,), jnp.float32).at[idx].set(vals)
    new_residual = acc - own
    return dense.reshape(shape).astype(dtype), new_residual


def barrier(*, axes: Optional[AxisSpec] = None, process_set=None):
    """Synchronization barrier (BarrierOp analogue).

    Returns a scalar that data-depends on every worker having reached this
    point; consume it (e.g. ``jax.block_until_ready``) to enforce ordering.
    Under SPMD every device executes the program, so a process-set barrier
    synchronizes the full mesh.
    """
    axes, _ = _resolve(axes, process_set)
    return lax.psum(jnp.ones((), jnp.int32), axes)


def ppermute(x, perm, *, axes: Optional[AxisSpec] = None):
    """Point-to-point permutation over the flat axis (ring building block)."""
    axes, _ = _resolve(axes)
    if len(axes) != 1:
        raise NotImplementedError("ppermute requires a flat mesh axis")
    return lax.ppermute(x, axes[0], perm)


def desync_check(x, *, axes: Optional[AxisSpec] = None):
    """In-step desync probe: scalar bool, True when ``x`` is NOT
    bit-identical on every mesh member.

    Debug-mode companion of :func:`horovod_tpu.core.desync.check_desync`
    (SURVEY.md 5.2's "psum of hashes"): an integer bit-sum of the local
    array compared via pmax/pmin -- two cheap scalar collectives, so it can
    run every step under ``HOROVOD_CHECK_DESYNC=1`` without moving data.
    """
    axes, _ = _resolve(axes)
    x = jnp.asarray(x)
    nbits = x.dtype.itemsize * 8
    if x.dtype == jnp.bool_:
        bits = x.astype(jnp.int32)
    elif nbits >= 32:
        # Wide elements bitcast to int32 words (64-bit dtypes gain a
        # trailing length-2 dim), so no high bits are dropped.
        bits = lax.bitcast_convert_type(x, jnp.int32)
    elif jnp.issubdtype(x.dtype, jnp.floating):
        bits = lax.bitcast_convert_type(
            x, jnp.dtype(f"int{nbits}")).astype(jnp.int32)
    else:
        bits = x.astype(jnp.int32)
    # Wrapping uint32 sum of position-weighted words: exact (associative)
    # regardless of reduction order, unlike a float checksum, and the
    # per-position odd multiplier (Knuth hash constant; bijective mod 2^32)
    # makes permutations of the same values visible -- a plain bit-sum
    # would pass rank 0 holding [a, b] against rank 1 holding [b, a].
    flat = bits.ravel()
    if flat.size:
        u = lax.bitcast_convert_type(flat, jnp.uint32)
        # |1 keeps every weight ODD (hence invertible mod 2^32): i*K+1 is
        # even at odd i, which would zero out top-bit-only differences.
        w = (jnp.arange(flat.size, dtype=jnp.uint32)
             * jnp.uint32(2654435761)) | jnp.uint32(1)
        c = jnp.sum(u * w, dtype=jnp.uint32)
    else:
        c = jnp.zeros((), jnp.uint32)
    hi, lo = c, c
    for a in axes:
        hi = lax.pmax(hi, a)
        lo = lax.pmin(lo, a)
    return hi != lo
