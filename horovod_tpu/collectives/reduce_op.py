"""Reduction-op constants (``hvd.Sum / Average / Adasum / Min / Max / Product``).

Parity with the reference's ``ReduceOp`` surface exposed from
``horovod/torch/mpi_ops.py`` / ``horovod/common/message.h::RequestType``.
"""

from __future__ import annotations

import enum


class ReduceOp(enum.Enum):
    AVERAGE = "average"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    PRODUCT = "product"
    ADASUM = "adasum"

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.value


# Module-level aliases matching the hvd.* names.
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
Adasum = ReduceOp.ADASUM
