"""Host-level (eager) collective API with async handles.

This is the analogue of the reference's enqueue surface
(``horovod/common/operations.cc::EnqueueTensorAllreduce`` + the
``handle``/``synchronize``/``poll`` machinery of
``horovod/torch/mpi_ops.py``) for code running *outside* a traced step --
parameter broadcasts, metric averaging, tests.

Data model ("rank-stacked" arrays):

* single process: the input carries a leading axis of length
  ``process_set.size()`` -- element ``i`` is rank ``i``'s tensor.  The
  result has the same shape (every rank's post-collective value).
* multi-process: each process passes its *local* stack of shape
  ``[local_ranks_in_set, ...]`` and receives its local stack back; the
  global array is assembled with ``jax.make_array_from_process_local_data``.

Dispatch path: the request signature (op kind, name, shape, dtype, reduce
op, process set -- exactly the reference's ``Request`` wire fields) keys the
:class:`~horovod_tpu.controller.cache.ExecutableCache`; a hit reuses the
compiled ``shard_map`` program (ResponseCache bitvector fast path
analogue), a miss traces + compiles one.  JAX dispatch is asynchronous, so
``*_async`` returns a handle immediately and ``synchronize`` blocks --
matching the reference's semantics without a background thread.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import ops as _ops
from .compression import Compression
from .reduce_op import ReduceOp, Average, Sum
from ..controller.cache import signature
from ..core import process_sets as _ps
from ..core import stall as _stall
from ..core.state import global_state
from ..parallel.mesh import HVD_AXIS


def _is_multiprocess(mesh: Mesh) -> bool:
    return len({d.process_index for d in mesh.devices.flat}) > 1


def local_rank_count(ps=None) -> int:
    """Number of this process's devices in the set (= rows this process
    contributes to a rank-stacked eager input in multi-process mode).

    Returns 0 when this process owns NO member device -- including the
    case where every member device belongs to one OTHER process (a
    "single-process" member mesh seen from a non-member)."""
    ps = _ps.get_process_set(ps)
    mesh = ps.flat_mesh()
    me = jax.process_index()
    if not _is_multiprocess(mesh):  # all devices owned by ONE process
        owner = mesh.devices.flat[0].process_index
        return int(mesh.devices.size) if owner == me else 0
    return sum(1 for d in mesh.devices.flat if d.process_index == me)


def replicated_stack(leaf, ps=None) -> np.ndarray:
    """Stack one host value into the correctly-sized rank-stacked input for
    the current mode (all ranks in single-process; local ranks otherwise)."""
    x = np.asarray(leaf)
    k = local_rank_count(ps)
    return np.broadcast_to(x[None], (k,) + x.shape)


def _to_global(x, mesh: Mesh):
    """Assemble the rank-stacked global array on the eager mesh."""
    n = int(mesh.devices.size)
    sharding = NamedSharding(mesh, P(HVD_AXIS))
    if _is_multiprocess(mesh):
        local = np.asarray(x)
        me = jax.process_index()
        k = sum(1 for d in mesh.devices.flat if d.process_index == me)
        if local.ndim == 0 or local.shape[0] != k:
            raise ValueError(
                f"multi-process eager collectives take this process's local "
                f"rank stack: expected leading axis {k}, got shape "
                f"{local.shape} (use horovod_tpu.replicated_stack for "
                f"replicated host values)")
        global_shape = (n,) + local.shape[1:]
        return jax.make_array_from_process_local_data(
            sharding, local, global_shape)
    x = jnp.asarray(x)
    if x.ndim == 0 or x.shape[0] != n:
        raise ValueError(
            f"eager collectives take rank-stacked input: expected leading "
            f"axis {n} (process-set size), got shape {x.shape}")
    return jax.device_put(x, sharding)


def _run(kind: str, x, name: Optional[str], ps, per_rank_fn, op_label: str,
         out_rank_stacked: bool = True, publish_meta: Optional[dict] = None):
    """Shared eager dispatch: cache lookup -> shard_map program -> run.

    ``publish_meta``: replay metadata for joined ranks (join mode only) --
    published to the coordination KV store under this op's fence sequence
    number before dispatch, so drained ranks can mirror the collective.
    """
    from . import joinop as _join
    st = global_state()
    ps = _ps.get_process_set(ps)
    mesh = ps.flat_mesh()
    def _publish_abort(e: Exception) -> None:
        _join.publish(mesh, {"kind": "abort",
                             "message": f"{type(e).__name__}: {e}"})

    if publish_meta is None:
        arr = _to_global(x, mesh)
    else:
        # Join phase: drained ranks are already blocked on this op's
        # sequence slot (their presence round matched ours).  Validate
        # BEFORE publishing so a bad input publishes an abort record --
        # not op metadata they would replay against a never-dispatched
        # collective -- and publish an abort for any later dispatch
        # failure too (best effort: a drained rank that fetched the op
        # metadata before the overwrite lands surfaces the failure as a
        # transport error/timeout instead).
        try:
            arr = _to_global(x, mesh)
        except Exception as e:
            _publish_abort(e)
            raise
        _join.publish(mesh, publish_meta)
    key = signature(kind, name, (tuple(arr.shape), str(arr.dtype)), op_label,
                    ps.name)
    timeline = st.timeline

    def build():
        def spmd(block):
            # block: [1, ...] -- this device's rank tensor.
            y = per_rank_fn(block[0])
            return y[None]
        f = jax.shard_map(spmd, mesh=mesh, in_specs=P(HVD_AXIS),
                          out_specs=P(HVD_AXIS))
        return jax.jit(f)

    from ..timeline import spans as _spans
    rec = _spans.recorder()
    tags = {"rank": rec.rank, "step": rec.step, "leg": kind}
    try:
        t_neg = time.perf_counter()
        if timeline:
            with timeline.range(name or kind, "NEGOTIATE_" + kind.upper(),
                                args=tags):
                fn = st.cache.get_or_build(key, build)
            t_exec = time.perf_counter()
            with timeline.range(name or kind, kind.upper(), args=tags):
                out = fn(arr)
        else:
            fn = st.cache.get_or_build(key, build)
            t_exec = time.perf_counter()
            out = fn(arr)
    except Exception as e:
        if publish_meta is not None:
            _publish_abort(e)
        raise
    t_done = time.perf_counter()
    rec.add("negotiate", t_exec - t_neg, leg=kind)
    rec.add("exchange", t_done - t_exec, leg=kind)
    with _eager_stats_lock:
        _eager_stats["ops"] += 1
    if timeline:
        with timeline.range(name or kind, "FENCE", args=tags):
            _eager_fence(mesh, out)
    else:
        _eager_fence(mesh, out)
    rec.add("fence", time.perf_counter() - t_done, leg=kind)
    return out


def _mesh_platform(mesh: Mesh) -> str:
    """Hardware platform backing the eager mesh ("cpu"/"tpu"/"gpu")."""
    return getattr(mesh.devices.flat[0], "platform", "cpu")


def _transport_needs_fence(mesh: Mesh) -> bool:
    """Does this mesh's collective transport need post-dispatch
    serialization?  The two hazards fenced below are properties of the
    multi-process CPU (Gloo-style) transport; TPU/GPU collectives run on
    compiler-scheduled dedicated channels and never interleave."""
    return _mesh_platform(mesh) == "cpu"


def _eager_fence(mesh: Mesh, out) -> None:
    """Serialize cross-process eager collectives (backend-scoped).

    Two hazards on the multi-process CPU (Gloo) backend, both observed
    as "op.preamble.length <= op.nbytes ... distributed collective
    mismatch" aborts:
     1. separately-compiled programs reuse the same collective channel
        tags, so two programs in flight at once interleave their Gloo
        messages across processes;
     2. consecutive executions of even the SAME program reuse slots, and
        local completion on one rank does not imply the peer drained its
        tail messages -- the next dispatch can race them.
    block_until_ready closes (1) locally; the coordination-service
    barrier (gRPC, independent of the Gloo transport) closes (2) by
    ensuring every participant fully finished before anyone starts the
    next collective.  In-step fused collectives (one program per step)
    are unaffected; single-process paths skip this entirely, and a
    TPU/GPU-backed mesh skips the block + barrier (its channels cannot
    interleave) while still advancing the fence SEQUENCE -- join replay
    keys op metadata on that counter, so it must tick identically on
    every backend (see :func:`_coordination_fence`).
    """
    if not _is_multiprocess(mesh):
        return
    if _transport_needs_fence(mesh):
        jax.block_until_ready(out)
    _coordination_fence(mesh)


_fence_lock = threading.Lock()
_fence_seq: Dict[tuple, int] = {}

_eager_stats_lock = threading.Lock()
_eager_stats = {"ops": 0}


def eager_op_stats() -> dict:
    """Cumulative eager-plane accounting since the last reset:
    ``ops`` = collective dispatches through the shared ``_run`` path,
    ``fences`` = coordination-fence sequence advances summed over every
    participant set.  Feeds the ``horovod_eager_*`` metric families."""
    with _eager_stats_lock:
        ops = _eager_stats["ops"]
    with _fence_lock:
        fences = sum(_fence_seq.values())
    return {"ops": ops, "fences": fences}


def reset_fences() -> None:
    """Reset barrier sequence numbers.  Called by ``hvd.shutdown()``: after
    an elastic re-init, a restarted worker starts counting from zero, so a
    survivor carrying the old counts would wait at differently-named
    barriers forever."""
    from . import joinop as _join
    reset_deferred()
    with _fence_lock:
        _fence_seq.clear()
    with _eager_stats_lock:
        _eager_stats["ops"] = 0
    _join.reset()


def _peek_next_seq(procs: tuple) -> int:
    """The fence sequence number the NEXT collective on ``procs`` will use
    (the key joined ranks watch for replay metadata)."""
    with _fence_lock:
        return _fence_seq.get(procs, 0) + 1


def _coordination_fence(mesh: Mesh) -> None:
    """Cross-process happens-before via the JAX coordination service.

    Every process whose devices appear in ``mesh`` joins a named barrier;
    the name carries a per-participant-set sequence number, which matches
    across processes because SPMD requires them to issue eager collectives
    in the same order.

    The sequence number advances on EVERY backend (it keys join-replay
    metadata slots, so active and drained ranks must count identically);
    the barrier WAIT itself is scoped to the CPU/Gloo transport that
    needs it (:func:`_transport_needs_fence`).
    """
    procs = tuple(sorted({d.process_index for d in mesh.devices.flat}))
    with _fence_lock:
        seq = _fence_seq[procs] = _fence_seq.get(procs, 0) + 1
    client = getattr(jax._src.distributed.global_state, "client", None)
    if client is None:  # pragma: no cover - not under jax.distributed
        return
    if not _transport_needs_fence(mesh):
        return
    name = "hvd_eager_fence_" + "_".join(map(str, procs)) + f"_{seq}"
    client.wait_at_barrier(name, 60_000, process_ids=list(procs))


def local_result(out) -> np.ndarray:
    """This process's portion of a rank-stacked result (multi-process), or
    the whole stack (single process)."""
    shards = sorted(out.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def one_row(out) -> np.ndarray:
    """One locally-addressable rank's row of a rank-stacked result.

    After a broadcast/allreduce every row is identical, so any local
    shard serves; used by the framework shims and the broadcast helpers
    (works multi-process, where the global array spans non-addressable
    devices)."""
    return np.array(np.asarray(out.addressable_shards[0].data)[0])


# ---------------------------------------------------------------------------
# Handle table (HandleManager analogue, horovod/torch/handle_manager.cc).
# ---------------------------------------------------------------------------

_handle_lock = threading.Lock()
_handle_counter = itertools.count(1)
_handles: Dict[int, Any] = {}

_PENDING = object()  # handle value: enqueued in _deferred, not yet dispatched
_ABSENT = object()   # pop default: distinguishes "no such handle" from pending


def _alloc_handle(value) -> int:
    with _handle_lock:
        h = next(_handle_counter)
        _handles[h] = value
        return h


def synchronize(handle: int):
    """Block until the async op completes and return its result.

    A deferred op whose flush failed raises its error here, ONCE -- the
    entry is consumed either way (retrying a consumed handle is a
    KeyError, matching an unknown handle).
    """
    flush_error = None
    try:
        flush_deferred()
    except Exception as e:  # KeyboardInterrupt/SystemExit propagate
        # The flush error was written into every affected handle; deliver
        # THIS handle's outcome (its op may have dispatched fine before a
        # later op failed).  A handle the failed flush never touched
        # propagates the flush error itself.
        flush_error = e
    if flush_error is None:
        with _handle_lock:
            value = _handles.pop(handle)   # KeyError: unknown/consumed
    else:
        with _handle_lock:
            value = _handles.pop(handle, _ABSENT)
        if value is _ABSENT:
            # Unknown/already-consumed handles stay a KeyError even when
            # the flush failed: the flush error belongs to the ops it
            # aborted, not to a caller retrying a spent handle.
            raise KeyError(handle)
        if value is _PENDING:
            raise flush_error
    if isinstance(value, BaseException):
        raise value
    with _stall.watched(f"synchronize(handle={handle})"):
        from ..elastic import chaos as _chaos
        _chaos.raise_if_armed()  # injected at=sync comm fault
        return jax.block_until_ready(value)


def poll(handle: int) -> bool:
    """True when the async op has finished (result ready to fetch).

    Polling a still-deferred op dispatches the pending batch first (the
    reference's PollHandle likewise guarantees progress -- a caller
    spinning on poll() must not livelock on an op that was never
    submitted to the cycle).  A flush failure reports True: the error is
    stored in the handle and raises at synchronize()."""
    with _handle_lock:
        pending = _handles.get(handle) is _PENDING
    if pending:
        try:
            flush_deferred()
        except Exception:  # delivered via synchronize; interrupts raise
            return True
    with _handle_lock:
        value = _handles.get(handle)
    if value is None:
        return True
    if isinstance(value, BaseException):
        return True
    try:
        return all(not a.is_deleted() and a.is_ready()
                   for a in jax.tree.leaves(value))
    except AttributeError:  # pragma: no cover - older jax
        jax.block_until_ready(value)
        return True


# ---------------------------------------------------------------------------
# Deferred async dispatch (cycle batching for the presence protocol).
#
# Reference analogue: EnqueueTensorAllreduce puts the request on the
# background loop's queue and RunLoopOnce negotiates EVERYTHING pending in
# one controller round per cycle.  Here the control-plane cost is the join
# presence round (~ms on localhost Gloo, measured in docs/benchmarks.md
# "Eager control plane"), and the grouped/fused entry points already
# amortize it via joinop.flush -- but a loop of ungrouped ``*_async`` ops
# paid one round each.  Deferring the dispatch until a flush point
# (synchronize/poll, any sync collective, hvd.join, or the capacity cap)
# lets ONE presence round cover every op enqueued since the last flush,
# exactly the reference's async contract: an async op is only guaranteed
# to have run after its synchronize().
#
# Only ops the presence protocol applies to are deferred (multi-process,
# global set, join enabled): everywhere else JAX dispatch is already
# async and immediate dispatch is strictly better.  Flush points are
# program-order-deterministic (SPMD processes enqueue identical op
# sequences), so every process cuts identical batches -- a requirement,
# since the batch size is published to drained ranks via the flush-size
# protocol.
# ---------------------------------------------------------------------------

_deferred_lock = threading.Lock()
_deferred: List[tuple] = []          # (handle, entry) in issue order
_MAX_DEFERRED = 512                  # capacity flush (deterministic: count)
_flush_lock = threading.RLock()      # serializes flushes across threads
_flush_tls = threading.local()       # .active: THIS thread is mid-flush
_fused_meta_tls = threading.local()  # .extra: in-flight fused dispatch meta

_fuse_stats_lock = threading.Lock()
_fuse_stats = {"flushes": 0, "fused_buckets": 0, "fused_ops": 0,
               "singleton_ops": 0}


def deferred_fuse_stats() -> dict:
    """Cumulative fused-flush accounting since the last reset: flushes
    run, fused buckets dispatched, ops that rode a fused bucket, ops
    dispatched per-op (singletons).  Mirrors the ``deferred_fused_*``
    timeline counters for callers without a timeline."""
    with _fuse_stats_lock:
        return dict(_fuse_stats)


@dataclasses.dataclass
class _DeferredAllreduce:
    """Structured deferred entry.

    Round-6: carries the request fields instead of an opaque thunk, so
    ``flush_deferred`` can group compatible pending ops through the
    fusion planner (the reference's fusion-buffer cycle groups on the
    same Request fields).  ``dispatch`` reproduces the exact per-op call
    for the unfused/fallback path."""
    x: Any
    op: Any
    name: Optional[str]
    process_set: Any          # resolved ProcessSet
    prescale: float
    postscale: float
    compression: Any

    def fuse_key(self) -> tuple:
        """Ops fuse only when every program-changing parameter matches
        (kind, dtype, reduce op, scale factors, codec, process set) --
        the bucket then compiles, publishes, and replays as ONE
        collective."""
        return ("allreduce", str(jnp.dtype(self.x.dtype)), str(self.op),
                float(self.prescale), float(self.postscale),
                self.compression.__name__, self.process_set.name)

    def dispatch(self):
        return allreduce(self.x, self.op, name=self.name,
                         process_set=self.process_set,
                         prescale_factor=self.prescale,
                         postscale_factor=self.postscale,
                         compression=self.compression)


def _deferred_fuse_enabled() -> bool:
    st = global_state()
    if st.config is not None:
        return st.config.deferred_fuse
    from ..core.config import _env_bool
    return _env_bool("DEFERRED_FUSE", True)


def _deferred_fuse_threshold() -> int:
    """Per-rank bucket byte cap for the fused flush
    (HOROVOD_DEFERRED_FUSE_THRESHOLD; 0 = follow the fusion threshold,
    autotuner included)."""
    st = global_state()
    if st.config is not None and st.config.deferred_fuse_threshold > 0:
        return st.config.deferred_fuse_threshold
    from ..controller import fusion as _fusion
    return _fusion._threshold()


def _defer_applies(ps) -> bool:
    """Should an ``*_async`` op on ``ps`` defer to the batched flush?
    Exactly when the presence protocol applies (multi-process, global
    set, join enabled): everywhere else JAX dispatch is already async
    and immediate dispatch is strictly better.  Separate seam so tests
    can force the deferred path on a single-process mesh."""
    from . import joinop as _join
    return _join._applies(ps)


def _in_flush() -> bool:
    """True on the thread currently executing flush_deferred's dispatch
    loop.  Must be thread-local: a CONCURRENT thread's collective is not
    reentrant -- it must block on the flush lock, not skip the flush."""
    return getattr(_flush_tls, "active", False)


def _defer(entry) -> int:
    """Enqueue a deferred op: a :class:`_DeferredAllreduce` record
    (fusable at flush) or a bare thunk (always per-op)."""
    h = _alloc_handle(_PENDING)
    with _deferred_lock:
        _deferred.append((h, entry))
        full = len(_deferred) >= _MAX_DEFERRED
    if full:
        flush_deferred()
    return h


def deferred_count() -> int:
    with _deferred_lock:
        return len(_deferred)


def reset_deferred() -> None:
    """Drop undispatched async ops (``hvd.shutdown()``): an async op is
    only guaranteed dispatched after synchronize/poll, and flushing here
    could hang against peers that already shut down."""
    with _deferred_lock:
        dropped = list(_deferred)
        _deferred.clear()
    with _handle_lock:
        for h, _ in dropped:
            _handles.pop(h, None)
    with _fuse_stats_lock:
        for key in _fuse_stats:
            _fuse_stats[key] = 0


def _deferred_error(handle: int, cause: BaseException,
                    reason: str) -> RuntimeError:
    """Fresh per-handle error for a failed flush.

    Every affected handle gets its OWN exception object (chained to the
    shared cause) -- raising one shared instance from several
    ``synchronize()`` calls would accrete conflicting tracebacks and make
    each raise look like a re-raise of the previous one.
    """
    err = RuntimeError(
        f"deferred async op (handle {handle}) {reason}: {cause!r}")
    err.__cause__ = cause
    return err


@dataclasses.dataclass
class _FlushUnit:
    """One collective dispatch within a flush: a fused bucket of
    compatible ops, or a single op on the per-op path.  ``leg`` is the
    unit's exchange-plan IR row (fused buckets only) -- the scheduler
    orders units by its cost model under the default bandwidth mode."""
    pos: int                       # issue position of the first member
    handles: List[int]
    dispatch: Callable[[], Dict[int, Any]]
    fused: bool = False
    leg: Any = None                # Optional[fusion.ExchangeLeg]


def _single_unit(pos: int, h: int, entry) -> _FlushUnit:
    d = entry.dispatch if isinstance(entry, _DeferredAllreduce) else entry
    return _FlushUnit(pos, [h], lambda h=h, d=d: {h: d()})


def _fused_unit(bucket, widths, k: int) -> _FlushUnit:
    """ONE collective for a planner bucket of compatible deferred ops.

    The member rank-stacks reshape to ``[k, width]`` rows and concatenate
    into one ``[k, sum(widths)]`` payload; a single :func:`allreduce`
    carries it (one presence slot, one fence).  Results slice back per
    handle through a jitted unfuse program (eager slicing of a
    multi-process global array is not allowed outside jit) memoized in
    the shared executable cache.  The bucket name is derived from the
    first member's issue position -- deterministic across SPMD processes,
    stable across identical flushes so the compiled program and unfuse
    slicer both cache-hit.
    """
    pos = min(p for p, _, _ in bucket)
    handles = [h for _, h, _ in bucket]
    recs = [r for _, _, r in bucket]
    r0 = recs[0]
    name = f"deferred_fused.{jnp.dtype(r0.x.dtype).name}.{pos}"
    widths = [int(w) for w in widths]
    tails = [tuple(int(d) for d in r.x.shape[1:]) for r in recs]

    def dispatch():
        host = all(isinstance(r.x, np.ndarray) for r in recs)
        cat = np.concatenate if host else jnp.concatenate
        flats = [(r.x if host else jnp.asarray(r.x)).reshape(k, -1)
                 for r in recs]
        fused = cat(flats, axis=1)
        # Publish the fused layout with the op metadata: a drained rank
        # replays the bucket-level collective bitwise from kind + fused
        # shape (joinop._replay also cross-checks the widths).
        _fused_meta_tls.extra = {"fused_ops": len(recs),
                                 "fused_widths": widths}
        try:
            red = allreduce(fused, r0.op, name=name,
                            process_set=r0.process_set,
                            prescale_factor=r0.prescale,
                            postscale_factor=r0.postscale,
                            compression=r0.compression)
        finally:
            _fused_meta_tls.extra = None
        st = global_state()
        key = signature("deferred_unfuse", name,
                        (tuple(red.shape), str(red.dtype)),
                        f"{widths}|{tails}", r0.process_set.name)

        def build():
            def unfuse(buf):
                out, off = [], 0
                for w, tail in zip(widths, tails):
                    out.append(buf[:, off:off + w].reshape(
                        (buf.shape[0],) + tail))
                    off += w
                return out
            return jax.jit(unfuse)

        vals = st.cache.get_or_build(key, build)(red)
        return dict(zip(handles, vals))

    # Plan-IR row for the fused payload: one flat allreduce of the
    # [k, sum(widths)] concat at this bucket's wire dtype.  Pure in the
    # member shapes/codec, so every SPMD process derives the same row.
    from ..controller import fusion as _fusion
    leg = _fusion.plan_exchange(
        "flat", size=k * sum(widths),
        dtype=jnp.dtype(r0.x.dtype).name,
        compression=r0.compression).legs[0]
    return _FlushUnit(pos, handles, dispatch, fused=True, leg=leg)


def _plan_flush_units(pending, fuse: bool) -> List[_FlushUnit]:
    """Group pending deferred entries into dispatch units.

    Compatible structured ops (same :meth:`_DeferredAllreduce.fuse_key`)
    route through the shared fusion planner
    (:func:`~horovod_tpu.controller.fusion.plan_eager_flush`) and pack
    into per-rank buckets of at most the deferred-fuse threshold: one
    fused collective + one fence per bucket.  Everything else -- opaque
    thunks, mismatched keys, inputs that are not a well-formed local rank
    stack -- keeps the per-op path, as does any bucket with a single
    member (no concat/slice overhead for the trivial case).  The grouping
    is pure in issue order + op signatures, so every SPMD process cuts
    identical units -- required, since the unit count is published to
    drained ranks as the flush size.  Units dispatch in the issue order
    of their first member.
    """
    from ..controller import fusion as _fusion
    units: List[_FlushUnit] = []
    groups: Dict[tuple, List[tuple]] = {}
    for pos, (h, entry) in enumerate(pending):
        if not (fuse and isinstance(entry, _DeferredAllreduce)):
            units.append(_single_unit(pos, h, entry))
            continue
        k = local_rank_count(entry.process_set)
        shape = getattr(entry.x, "shape", ())
        if k < 1 or len(shape) < 1 or shape[0] != k:
            # Not a local rank stack: the per-op path raises the same
            # error immediate dispatch would have.
            units.append(_single_unit(pos, h, entry))
            continue
        groups.setdefault(entry.fuse_key(), []).append((pos, h, entry))
    threshold = _deferred_fuse_threshold()
    for members in groups.values():
        if len(members) == 1:
            units.append(_single_unit(*members[0]))
            continue
        recs = [entry for _, _, entry in members]
        k = local_rank_count(recs[0].process_set)
        spec = _fusion.plan_eager_flush(
            [r.x for r in recs], k, threshold,
            extra=(recs[0].process_set.name,))
        for _dt, lspecs in spec.buffers:
            if len(lspecs) == 1:
                units.append(_single_unit(*members[lspecs[0].index]))
                continue
            units.append(_fused_unit([members[s.index] for s in lspecs],
                                     [s.size for s in lspecs], k))
    if _fusion.exchange_schedule_mode() == "bandwidth":
        # Bandwidth-ordered issue (HOROVOD_EXCHANGE_SCHEDULE=program
        # restores pure issue order): costliest planned legs dispatch
        # first so their wire time overlaps the cheaper units' host
        # glue.  Pure in the plan rows + issue order -- every SPMD
        # process cuts the identical sequence, which the drained-rank
        # protocol requires.  Payloads are untouched; only issue order
        # moves.
        units.sort(key=lambda u: (
            -_fusion.leg_cost_seconds(u.leg) if u.leg is not None
            else 0.0, u.pos))
    else:
        units.sort(key=lambda u: u.pos)
    return units


def _note_flush(units: List[_FlushUnit]) -> None:
    """Account the flush plan (module stats + timeline counters)."""
    fused = [u for u in units if u.fused]
    n_fused_ops = sum(len(u.handles) for u in fused)
    n_single = len(units) - len(fused)
    with _fuse_stats_lock:
        _fuse_stats["flushes"] += 1
        _fuse_stats["fused_buckets"] += len(fused)
        _fuse_stats["fused_ops"] += n_fused_ops
        _fuse_stats["singleton_ops"] += n_single
    tl = global_state().timeline
    if tl:
        tl.counters({"deferred_fused_buckets": len(fused),
                     "deferred_fused_ops": n_fused_ops,
                     "deferred_singleton_ops": n_single})


def flush_deferred() -> None:
    """Dispatch every deferred async op behind ONE presence round.

    Serialized under an RLock: a REENTRANT call (a unit's own dispatch
    re-entering via ``_join_sync``/``joinop.flush`` on the flushing
    thread) sees the thread-local flag and returns; a CONCURRENT thread's
    ``synchronize``/``poll``/collective blocks here until the in-flight
    flush lands its results -- returning early would let it pop the raw
    ``_PENDING`` sentinel as the op's value, or corrupt the in-flight
    joinop flush accounting.

    Round-6: compatible pending ops FUSE (see :func:`_plan_flush_units`);
    the published flush size is the number of dispatch UNITS, and each
    fused unit publishes bucket-level metadata so drained ranks replay
    one identical fused collective per bucket.  Results scatter back per
    handle under the existing error-stamping protocol: every handle in a
    failed unit gets its own error chained to the cause, handles in later
    units get "aborted" errors.
    """
    with _flush_lock:
        if _in_flush():
            return
        with _deferred_lock:
            pending = list(_deferred)
            _deferred.clear()
        if not pending:
            return
        from . import joinop as _join
        _flush_tls.active = True
        try:
            ps = _ps.get_process_set(None)
            units = _plan_flush_units(pending, _deferred_fuse_enabled())
            _note_flush(units)
            from ..timeline import spans as _spans
            rec = _spans.recorder()
            with _join.flush(ps, len(units)):
                err = None
                for i, unit in enumerate(units):
                    if err is None:
                        try:
                            fuse_key = (f"fused@{unit.pos}" if unit.fused
                                        else f"single@{unit.pos}")
                            with rec.span("bucket", name="deferred_flush",
                                          leg="deferred_flush",
                                          bucket_id=i, fuse_key=fuse_key):
                                values = unit.dispatch()
                        except BaseException as e:  # noqa: BLE001
                            err = e
                            values = {
                                h: _deferred_error(h, e,
                                                   "failed during flush")
                                for h in unit.handles}
                    else:
                        # Units after a failure never dispatch (the flush
                        # context publishes an abort for their slots);
                        # their synchronize() raises a fresh error chained
                        # to the op that sank the batch.
                        values = {
                            h: _deferred_error(
                                h, err, "aborted: an earlier op in the "
                                "flushed batch failed")
                            for h in unit.handles}
                    with _handle_lock:
                        for h, value in values.items():
                            if h in _handles:
                                _handles[h] = value
                if err is not None:
                    raise err
        except BaseException as e:
            # Context-entry failures (presence-round timeout, process-set
            # lookup during shutdown) reach here before the loop ran:
            # stamp the error into every handle still at the sentinel so
            # no synchronize() can return _PENDING as a "result".
            with _handle_lock:
                for h, _ in pending:
                    if _handles.get(h) is _PENDING:
                        _handles[h] = _deferred_error(
                            h, e, "aborted: flush failed before dispatch")
            raise
        finally:
            _flush_tls.active = False


# ---------------------------------------------------------------------------
# Public eager collectives.
# ---------------------------------------------------------------------------

def _join_sync(ps, kind: str, x, name: Optional[str], extra: dict = None):
    """Presence round + replay-metadata for join mode (JoinOp draining).

    Returns ``(k_active, meta, mask)``: ``k_active``/``mask`` are None
    when join handling does not apply (single process, replaying,
    non-global set); ``meta`` is None unless some rank has joined
    (k < set size), in which case it is the dict to publish for drained
    ranks to replay.
    """
    from . import joinop as _join
    if not _in_flush():
        # A sync collective is a flush point: pending deferred async ops
        # must dispatch first (program order; same point on every SPMD
        # process) so their presence round precedes this op's.
        flush_deferred()
    ps = _ps.get_process_set(ps)
    mask = _join.sync(ps)
    if mask is None:
        return None, None, None
    k = int(mask.sum())
    if k >= ps.size():
        return k, None, mask
    xa = np.asarray(x)
    meta = {"kind": kind, "name": name,
            "shape": (ps.size(),) + tuple(xa.shape[1:]),
            "dtype": str(xa.dtype)}
    if extra:
        meta.update(extra)
    fused_extra = getattr(_fused_meta_tls, "extra", None)
    if fused_extra:
        # A fused deferred-flush bucket is in flight on this thread:
        # publish its layout (op count + per-rank widths) with the op
        # metadata so drained ranks replay the bucket-level collective.
        meta.update(fused_extra)
    return k, meta, mask


def _join_abort(ps, message: str):
    """Raise after a presence round without leaving drained ranks hanging.

    A post-presence error on the active side must still publish SOMETHING
    at the op's sequence slot -- drained ranks are already blocked on the
    metadata key and would otherwise stall until HOROVOD_JOIN_TIMEOUT and
    then desync.  Publish an abort record (they re-raise it) and raise
    locally; every active rank does the same (SPMD), overwrites benign.
    """
    from . import joinop as _join
    _join.publish(_ps.get_process_set(ps).flat_mesh(),
                  {"kind": "abort", "message": message})
    raise RuntimeError(message)


def allreduce(x, op: ReduceOp = Average, *, name: Optional[str] = None,
              process_set=None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0, compression=Compression.none):
    ps = _ps.get_process_set(process_set)
    k, jmeta, _mask = _join_sync(ps, "allreduce", x, name)
    if jmeta is not None:
        if op is Average:
            # Mean over the ranks that actually contributed (reference
            # JoinOp behavior): the traced op divides by the full size n,
            # so rescale by n/k.  Ill-defined for truncating int division.
            if np.issubdtype(np.asarray(x).dtype, np.integer):
                _join_abort(ps, "integer-dtype Average while ranks are "
                                "joined is unsupported (truncating rescale "
                                "is ill-defined)")
            postscale_factor *= ps.size() / k
        jmeta.update(op=str(op), pre=prescale_factor,
                     post=postscale_factor,
                     compression=compression.__name__)
        from .compression import is_powersgd, powersgd_factor_widths
        if is_powersgd(compression):
            # Replay metadata for the low-rank codec: a drained rank
            # re-traces the factor exchange from shape alone, so publish
            # the factor widths (rank x matricized dims) for the replay
            # cross-check in joinop._replay.
            row = int(np.prod(np.asarray(x).shape[1:], dtype=np.int64))
            jmeta.update(factor_widths=list(
                powersgd_factor_widths(max(row, 1), compression.rank)))

    def per_rank(t):
        from .compression import is_fp8, is_powersgd, is_topk
        from .reduce_op import Adasum as _Adasum
        if is_powersgd(compression) or is_topk(compression):
            if op is _Adasum:
                raise NotImplementedError(
                    "error-feedback codecs do not compose with Adasum")
            # Stateless form: the eager control plane has nowhere to
            # thread residual state, so the residual is dropped (same
            # one-shot semantics the autotuner's probe samples use).
            if is_powersgd(compression):
                out, _ = _ops.powersgd_allreduce(
                    t, op, rank=compression.rank, axes=(HVD_AXIS,),
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor)
            else:
                out, _ = _ops.topk_allreduce(
                    t, op, fraction=compression.fraction, axes=(HVD_AXIS,),
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor)
            return out
        if is_fp8(compression):
            if op is _Adasum:
                return _ops.allreduce(t, op, axes=(HVD_AXIS,),
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor,
                                      wire_codec="fp8")
            return _ops.fp8_allreduce(t, op, axes=(HVD_AXIS,),
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor)
        c, ctx = compression.compress(t)
        r = _ops.allreduce(c, op, axes=(HVD_AXIS,),
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
        return compression.decompress(r, ctx)
    # Every parameter that changes the compiled program must be in the
    # cache key (the reference's Request carries the same distinctions).
    label = (f"{op}|pre={prescale_factor}|post={postscale_factor}|"
             f"{compression.__name__}")
    return _run("allreduce", x, name, ps, per_rank, label,
                publish_meta=jmeta)


def allreduce_async(x, op: ReduceOp = Average, *, name=None, process_set=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    compression=Compression.none) -> int:
    ps_ = _ps.get_process_set(process_set)
    if not _in_flush() and _defer_applies(ps_):
        # Snapshot host inputs: the caller may mutate the buffer between
        # enqueue and flush (jax arrays are immutable; no copy needed).
        x_snap = x if isinstance(x, jax.Array) else np.array(x, copy=True)
        return _defer(_DeferredAllreduce(
            x_snap, op, name, ps_, prescale_factor, postscale_factor,
            compression))
    out = allreduce(x, op, name=name, process_set=process_set,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor, compression=compression)
    return _alloc_handle(out)


def grouped_allreduce(xs: Sequence, op: ReduceOp = Average, *, name=None,
                      process_set=None, compression=Compression.none,
                      to_host: bool = False, prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0):
    """Fused multi-tensor eager allreduce (grouped_allreduce parity).

    Tensors are fused per dtype (concatenating mixed dtypes would silently
    promote); each dtype bucket dispatches one collective.  NumPy inputs
    fuse on the HOST (one staging transfer per bucket instead of one per
    tensor -- each host->device transfer is a round-trip on the tunnelled
    TPU, and a ResNet-50 has ~160 gradient tensors).

    ``to_host=True`` additionally fetches each bucket's result once and
    returns per-tensor numpy views of this process's LOCAL rank-stack --
    the framework-shim path, where slicing the fused device array per
    tensor would cost one device->host round-trip each.
    """
    xs = list(xs)
    if not xs:
        return []
    reds, spec = _grouped_allreduce_buckets(
        xs, op, name=name, process_set=process_set, compression=compression,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor)
    return _unfuse_buckets(reds, spec, to_host=to_host)


def _grouped_allreduce_buckets(xs, op: ReduceOp = Average, *, name=None,
                               process_set=None,
                               compression=Compression.none,
                               prescale_factor: float = 1.0,
                               postscale_factor: float = 1.0):
    """Dispatch the per-dtype fused allreduces WITHOUT fetching: returns
    ``(bucket_results, spec)`` for :func:`_unfuse_buckets` -- the async
    framework-shim path keeps the device arrays in its handle and unfuses
    (one fetch per bucket) only at synchronize."""
    ps = _ps.get_process_set(process_set)
    # Inputs are rank-stacked: ALL ranks single-process, this process's
    # local ranks in multi-process mode -- flatten per leading row.
    k = local_rank_count(ps)
    host_in = all(isinstance(x, np.ndarray) for x in xs)
    if not host_in:
        xs = [jnp.asarray(x) for x in xs]
    plan = _bucket_layout(xs, k, ps)
    cat = np.concatenate if host_in else jnp.concatenate
    reds, spec = [], []
    from . import joinop as _join
    with _join.flush(ps, len(plan)):  # ONE presence round per flush
        for dt, idxs, widths, tails in plan:
            flats = [xs[i].reshape(k, -1) for i in idxs]
            fused = flats[0] if len(flats) == 1 else cat(flats, axis=1)
            reds.append(allreduce(
                fused, op, name=f"{name or 'grouped_allreduce'}.{dt.name}",
                process_set=process_set, compression=compression,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor))
            spec.append((idxs, widths, tails))
    return reds, (spec, len(xs))


def _bucket_layout(xs, k: int, ps):
    """Memoized dtype-bucket layout for the per-step eager hot path.

    The grouping (and every width/tail it implies) is pure in the input
    shapes/dtypes, the local rank count and the process set, yet was
    recomputed on every grouped call.  The plan lives in the shared fusion
    plan cache (``controller.fusion``'s ``ExecutableCache``), keyed on
    (shapes, dtypes, threshold, process set); hit/miss counters surface
    through :func:`horovod_tpu.controller.fusion.plan_cache_stats`.
    """
    from ..controller import fusion as _fusion
    cache = _fusion._get_plan_cache()
    key = _fusion.plan_key(xs, _fusion._threshold(),
                           extra=("eager_grouped", k, ps.name))

    def build():
        by_dtype: Dict[Any, List[int]] = {}
        for i, x in enumerate(xs):
            by_dtype.setdefault(jnp.dtype(x.dtype), []).append(i)
        return tuple(
            (dt, tuple(idxs),
             # width == reshape(k, -1).shape[1], computed without touching
             # array data
             tuple(int(np.prod(xs[i].shape, dtype=np.int64)) // k
                   for i in idxs),
             tuple(tuple(xs[i].shape[1:]) for i in idxs))
            for dt, idxs in by_dtype.items())

    return cache.get_or_build(key, build)


def _unfuse_buckets(reds, spec, to_host: bool = False):
    """Split fused bucket results back into per-tensor arrays.

    ``to_host=True`` fetches each bucket ONCE (``local_result``) and
    returns numpy local-rank stacks -- slicing the fused device array per
    tensor would cost one device->host round-trip each on the tunnelled
    TPU (~160 round-trips for a ResNet-50).
    """
    buckets, n = spec
    out: List[Any] = [None] * n
    for red, (idxs, widths, tails) in zip(reds, buckets):
        if to_host:
            red = local_result(red)             # ONE fetch per bucket
        off = 0
        for i, w, tail in zip(idxs, widths, tails):
            # Device path: ``red`` is rank-stacked over the GLOBAL set
            # (leading axis ps.size()); host path: the LOCAL stack.
            out[i] = red[:, off:off + w].reshape((red.shape[0],) + tail)
            off += w
    return out


def broadcast_fused(arrays, root_rank: int = 0, *, name=None,
                    process_set=None):
    """Fused-per-dtype eager broadcast of replicated host arrays.

    Returns the root-rank value of each input as a host numpy array.  One
    collective (and one staging round-trip) per dtype instead of one per
    array -- a per-array loop compiles one XLA program per distinct shape
    and pays per-transfer tunnel latency; this is the backing for every
    framework shim's ``broadcast_parameters`` / ``broadcast_variables``.
    """
    ps = _ps.get_process_set(process_set)
    arrays = [np.asarray(a) for a in arrays]
    out: List[Any] = [None] * len(arrays)
    by_dtype: Dict[Any, List[int]] = {}
    for i, a in enumerate(arrays):
        by_dtype.setdefault(a.dtype, []).append(i)
    from . import joinop as _join
    with _join.flush(ps, len(by_dtype)):
        for dt, idxs in sorted(by_dtype.items(), key=lambda kv: str(kv[0])):
            flat = np.concatenate([arrays[i].ravel() for i in idxs])
            res = broadcast(replicated_stack(flat, ps), root_rank,
                            name=f"{name or 'broadcast_fused'}.{dt}",
                            process_set=ps)
            row = one_row(res)
            off = 0
            for i in idxs:
                cnt = arrays[i].size
                out[i] = row[off:off + cnt].reshape(arrays[i].shape)
                off += cnt
    return out


def grouped_allgather(xs: Sequence, *, name=None, process_set=None):
    """Fused multi-tensor allgather (reference ``hvd.grouped_allgather``).

    Per-rank tensors are flattened and concatenated into one buffer, ONE
    collective gathers it, and each tensor's dim-0 concatenation is sliced
    back out -- the fusion-buffer treatment upstream gives grouped ops.

    The fused buffer is static-shape: every rank must pass the SAME
    per-tensor shapes (the reference's grouped gather also negotiates
    ragged dims -- here ragged first dims go through per-tensor
    :func:`allgatherv` instead).
    """
    xs = _as_stacks(xs)
    if not xs:
        return []
    ps = _ps.get_process_set(process_set)
    k = local_rank_count(ps)
    n = ps.size()
    _check_rank_stacked(xs, k, "grouped_allgather")
    out: List[Any] = [None] * len(xs)
    cat = np.concatenate if isinstance(xs[0], np.ndarray) \
        else jnp.concatenate
    from . import joinop as _join
    buckets = _dtype_buckets(xs)
    with _join.flush(ps, len(buckets)):
        for dt, idxs in buckets.items():
            flats = [xs[i].reshape(k, -1) for i in idxs]
            widths = [f.shape[1] for f in flats]
            fused = flats[0] if len(flats) == 1 else cat(flats, axis=1)
            g = allgather(fused,
                          name=f"{name or 'grouped_allgather'}.{dt.name}",
                          process_set=ps)            # [k, n*S]
            S = sum(widths)
            rows = g.reshape(g.shape[0], n, S)
            off = 0
            for i, w in zip(idxs, widths):
                piece = rows[:, :, off:off + w]      # [k, n, w]
                out[i] = piece.reshape(
                    (g.shape[0], n * xs[i].shape[1]) + xs[i].shape[2:])
                off += w
    return out


def _as_stacks(xs) -> List[Any]:
    """Normalize inputs: keep all-numpy lists on the host (fusing there
    costs one staging transfer per BUCKET instead of one per tensor --
    each transfer is a round-trip on the tunnelled TPU)."""
    xs = list(xs)
    if all(isinstance(x, np.ndarray) for x in xs):
        return xs
    return [jnp.asarray(x) for x in xs]


def _dtype_buckets(xs) -> Dict[Any, List[int]]:
    """Indices grouped per dtype (concatenating mixed dtypes would
    silently promote)."""
    by_dtype: Dict[Any, List[int]] = {}
    for i, x in enumerate(xs):
        by_dtype.setdefault(jnp.dtype(x.dtype), []).append(i)
    return by_dtype


def grouped_reducescatter(xs: Sequence, op: ReduceOp = Average, *,
                          name=None, process_set=None):
    """Fused multi-tensor reducescatter (``hvd.grouped_reducescatter``).

    Each tensor's dim 0 must divide by the set size.  Tensors reshape to
    ``[k, n, d0/n * tail]`` and concatenate on the last axis, so ONE
    scatter leaves every rank a contiguous fused shard that slices back
    into per-tensor shards.
    """
    xs = _as_stacks(xs)
    if not xs:
        return []
    ps = _ps.get_process_set(process_set)
    k = local_rank_count(ps)
    n = ps.size()
    _check_rank_stacked(xs, k, "grouped_reducescatter")
    out: List[Any] = [None] * len(xs)
    for x in xs:
        if x.shape[1] % n:
            raise ValueError(
                f"grouped_reducescatter needs dim 0 divisible by the set "
                f"size {n}, got {x.shape[1:]}")
    cat = np.concatenate if isinstance(xs[0], np.ndarray) \
        else jnp.concatenate
    from . import joinop as _join
    buckets = _dtype_buckets(xs)
    with _join.flush(ps, len(buckets)):
        for dt, idxs in buckets.items():
            parts = [xs[i].reshape(k, n, -1) for i in idxs]
            widths = [p.shape[2] for p in parts]
            fused = parts[0] if len(parts) == 1 else cat(parts, axis=2)
            red = reducescatter(
                fused, op,
                name=f"{name or 'grouped_reducescatter'}.{dt.name}",
                process_set=ps)                      # [k, 1, S] shards
            red = red.reshape(red.shape[0], -1)
            off = 0
            for i, w in zip(idxs, widths):
                shard = red[:, off:off + w]
                out[i] = shard.reshape(
                    (red.shape[0], xs[i].shape[1] // n) + xs[i].shape[2:])
                off += w
    return out


def _check_rank_stacked(xs, k: int, what: str) -> None:
    for x in xs:
        if x.ndim < 2 or x.shape[0] != k:
            raise ValueError(
                f"{what} takes rank-stacked inputs with leading axis {k} "
                f"(this process's local ranks); got shape {x.shape}")


def allgather(x, *, name=None, process_set=None):
    """Each rank contributes its slice; all receive the concatenation.

    Rank-stacked input ``[n, d0, ...]`` -> output ``[n, n*d0, ...]``.
    First dimensions must match; ragged inputs go through
    :func:`allgatherv` (the reference's ``hvd.allgather`` supports both
    through one entry point because its negotiation already exchanges
    sizes; here the ragged path is explicit).

    During a join phase, drained ranks contribute ZERO rows of sizes via
    :func:`allgatherv` (reference zero-size gather contribution); through
    this static-shape entry point they contribute zeros."""
    ps = _ps.get_process_set(process_set)
    _, jmeta, _mask = _join_sync(ps, "allgather", x, name)

    def per_rank(t):
        return _ops.allgather(t, axes=(HVD_AXIS,), axis=0)
    return _run("allgather", x, name, ps, per_rank, "gather",
                publish_meta=jmeta)


def allgather_value(a, *, name=None, process_set=None) -> np.ndarray:
    """Framework-shim helper: gather ONE per-process value (replicated
    across this process's local ranks) with ragged first dims allowed.
    Single-controller mode treats every rank as holding ``a``."""
    k = local_rank_count(process_set)
    return allgatherv([np.asarray(a)] * k, name=name,
                      process_set=process_set)


def allgatherv(arrs, *, name=None, process_set=None) -> np.ndarray:
    """Ragged allgather: per-rank arrays whose FIRST dims differ.

    Reference semantics (``MPIAllgather``/``NCCLAllgather`` with unequal
    first dims -- the reference gathers sizes during negotiation, then
    runs a gatherv): sizes are exchanged first, data is padded to the max
    and gathered, and every rank receives the dim-0 concatenation in rank
    order as a HOST array (ragged shapes cannot live on-device under
    XLA's static shapes).

    ``arrs``: single process -- a sequence of per-rank arrays (length =
    set size); multi-process -- this process's local per-rank sequence
    (usually one array, which may be passed bare).
    """
    ps = _ps.get_process_set(process_set)
    if hasattr(arrs, "shape"):  # a bare array (ndarray / jax.Array)
        arrs = [arrs]
    arrs = [np.asarray(a) for a in arrs]
    k = local_rank_count(ps)
    if len(arrs) != k:
        raise ValueError(
            f"allgatherv takes one array per local rank: expected {k}, "
            f"got {len(arrs)}")
    tail_shapes = {a.shape[1:] for a in arrs}
    dtypes = {a.dtype for a in arrs}
    if len(tail_shapes) > 1 or len(dtypes) > 1:
        raise ValueError("allgatherv arrays may differ only in dim 0; got "
                         f"shapes {[a.shape for a in arrs]}, "
                         f"dtypes {sorted(map(str, dtypes))}")
    from . import joinop as _join
    with _join.flush(ps, 2):  # sizes + data: one presence round
        # Phase 1: exchange sizes (the reference's negotiation does this).
        sizes = np.asarray([[a.shape[0]] for a in arrs], np.int32)
        all_sizes = local_result(
            allgather(sizes, name=f"{name or 'allgatherv'}.sizes",
                      process_set=ps))[0].ravel()
        max_len = int(all_sizes.max())
        # Phase 2: pad to the max and gather (one static-shape collective).
        tail = arrs[0].shape[1:]
        padded = np.zeros((k, max_len) + tail, arrs[0].dtype)
        for i, a in enumerate(arrs):
            padded[i, :a.shape[0]] = a
        g = allgather(padded, name=f"{name or 'allgatherv'}.data",
                      process_set=ps)
    rows = local_result(g)[0].reshape((ps.size(), max_len) + tail)
    return np.concatenate([rows[r, :all_sizes[r]]
                           for r in range(ps.size())], axis=0)


def broadcast(x, root_rank: int = 0, *, name=None, process_set=None):
    ps = _ps.get_process_set(process_set)
    # root_rank is a global rank (reference semantics); on the member-only
    # eager mesh it maps to the root's position within the set.
    if ps.is_global():
        root_pos = root_rank
        if not 0 <= root_rank < ps.size():
            raise ValueError(f"broadcast root_rank {root_rank} out of range "
                             f"for world size {ps.size()}")
    else:
        if root_rank not in ps.ranks:
            raise ValueError(f"broadcast root_rank {root_rank} is not a "
                             f"member of process set {ps.name!r} "
                             f"(ranks {ps.ranks})")
        root_pos = ps.ranks.index(root_rank)

    _, jmeta, mask = _join_sync(ps, "broadcast", x, name,
                                {"root": root_rank})
    if jmeta is not None and not mask[root_rank]:
        # A drained root would replay zeros; error like the reference (a
        # joined rank cannot be the source of new data).
        _join_abort(ps, f"broadcast root_rank {root_rank} has joined and "
                        "cannot source a broadcast")

    def per_rank(t):
        return _ops.broadcast(t, root_pos, axes=(HVD_AXIS,))
    return _run("broadcast", x, name, ps, per_rank, f"root{root_rank}",
                publish_meta=jmeta)


def reducescatter(x, op: ReduceOp = Average, *, name=None, process_set=None,
                  _join_k: Optional[int] = None):
    """``_join_k`` (internal): active-rank count during a join phase --
    Average then divides by the contributing ranks, not the full size."""
    ps = _ps.get_process_set(process_set)
    if _join_k is None:
        k, jmeta, _mask = _join_sync(ps, "reducescatter", x, name)
        if jmeta is not None:
            if op is Average:
                if np.issubdtype(np.asarray(x).dtype, np.integer):
                    _join_abort(ps, "integer-dtype Average while ranks "
                                    "are joined is unsupported")
                _join_k = k
            jmeta.update(op=str(op), jk=_join_k)
    else:
        jmeta = None  # replaying a drained rank's mirror call

    def per_rank(t):
        if _join_k:
            y = _ops.reducescatter(t, Sum, axes=(HVD_AXIS,))
            return y / jnp.asarray(_join_k, y.dtype)
        return _ops.reducescatter(t, op, axes=(HVD_AXIS,))
    return _run("reducescatter", x, name, ps, per_rank,
                f"{op}|jk={_join_k}", publish_meta=jmeta)


def alltoall(x, *, name=None, process_set=None):
    ps = _ps.get_process_set(process_set)
    _, jmeta, _mask = _join_sync(ps, "alltoall", x, name)

    def per_rank(t):
        return _ops.alltoall(t, axes=(HVD_AXIS,))
    return _run("alltoall", x, name, ps, per_rank, "a2a",
                publish_meta=jmeta)


def alltoallv(arrs, splits, *, name=None, process_set=None):
    """Uneven alltoall (reference ``hvd.alltoall(tensor, splits=...)``).

    Reference semantics (NCCLAlltoall with ``splits`` -- the negotiation
    exchanges counts, then a ragged exchange runs): split counts are
    allgathered first, data is padded to the global max split and exchanged
    with one static-shape alltoall, and each rank receives the rank-order
    concatenation of the splits addressed to it, plus the per-sender counts.

    Args:
      arrs: single process -- per-rank data arrays (length = set size);
        multi-process -- this process's local per-rank list.  Each is
        ``[total_r, ...]`` rows, the rank-order concatenation of splits.
      splits: matching per-rank int arrays ``[size]``; ``splits[r][i]``
        rows of ``arrs[r]`` go to global rank ``i``.

    Returns:
      ``(datas, recv_splits)``: per local rank ``r``, ``datas[r]`` is the
      HOST array concatenating what rank ``r`` received (in sender rank
      order) and ``recv_splits[r][j]`` says how many rows came from global
      rank ``j``.
    """
    ps = _ps.get_process_set(process_set)
    if hasattr(arrs, "shape"):
        arrs = [arrs]
    arrs = [np.asarray(a) for a in arrs]
    if hasattr(splits, "shape") and np.asarray(splits).ndim == 1:
        splits = [splits]
    splits = [np.asarray(s, np.int32) for s in splits]
    k = local_rank_count(ps)
    n = ps.size()
    if k == 0:  # non-member process: no sub-mesh participation
        if arrs or splits:
            raise ValueError("this process owns no member device; pass "
                             "empty arrs/splits")
        return [], []
    if len(arrs) != k or len(splits) != k:
        raise ValueError(
            f"alltoallv takes one array and one splits vector per local "
            f"rank: expected {k}, got {len(arrs)} arrays / {len(splits)} "
            f"splits")
    for a, s in zip(arrs, splits):
        if s.shape != (n,):
            raise ValueError(f"splits must have shape ({n},), got {s.shape}")
        if s.sum() != a.shape[0]:
            raise ValueError(
                f"splits must sum to the data rows (the rank-order "
                f"concatenation of splits): sum {int(s.sum())} != "
                f"{a.shape[0]} rows")
    tail_shapes = {a.shape[1:] for a in arrs}
    dtypes = {a.dtype for a in arrs}
    if len(tail_shapes) > 1 or len(dtypes) > 1:
        raise ValueError("alltoallv arrays may differ only in dim 0; got "
                         f"shapes {[a.shape for a in arrs]}, "
                         f"dtypes {sorted(map(str, dtypes))}")
    from . import joinop as _join
    with _join.flush(ps, 2):  # split matrix + exchange: one presence round
        # Phase 1: exchange the split matrix (negotiation analogue).  Row
        # r of ``all_splits`` is global rank r's splits vector.
        stacked = np.stack(splits)                  # [k, n]
        all_splits = local_result(
            allgather(stacked, name=f"{name or 'alltoallv'}.splits",
                      process_set=ps))[0].reshape(n, n)
        max_len = max(int(all_splits.max()), 1)
        tail = arrs[0].shape[1:]
        # Phase 2: pad each split to the max and exchange (one
        # static-shape alltoall).  Send layout per rank: [n, max_len, ...].
        padded = np.zeros((k, n, max_len) + tail, arrs[0].dtype)
        for r, (a, s) in enumerate(zip(arrs, splits)):
            off = 0
            for i, c in enumerate(s):
                padded[r, i, :c] = a[off:off + c]
                off += int(c)

        # Join phase: drained ranks replay this as a plain alltoall of
        # zeros on the padded shape (identical traced program) -- their
        # zero split rows in ``all_splits`` already make receivers take 0
        # rows from them.
        _, jmeta, _mask = _join_sync(ps, "alltoall", padded, name)

        def per_rank(t):
            return _ops.alltoall(t, axes=(HVD_AXIS,))
        out = _run("alltoallv", padded, name, ps, per_rank, "a2av",
                   publish_meta=jmeta)
    rows = local_result(out)                        # [k, n, max_len, ...]
    local_global_ranks = _local_member_positions(ps)
    datas, recv_splits = [], []
    for r in range(k):
        g = local_global_ranks[r]
        counts = all_splits[:, g]                   # what each sender sent me
        datas.append(np.concatenate(
            [rows[r, j, :counts[j]] for j in range(n)], axis=0))
        recv_splits.append(counts.copy())
    return datas, recv_splits


def alltoallv_row(data, splits, *, name=None, process_set=None):
    """Framework-shim helper: uneven alltoall of ONE per-process value
    (replicated across this process's local ranks, like
    :func:`replicated_stack` for the even collectives).

    Returns host arrays ``(received, received_splits)`` for this process's
    first local rank -- the single-controller row the torch/TF/mxnet
    wrappers hand back.
    """
    data = np.asarray(data)
    sp = np.asarray(splits, np.int32)
    k = local_rank_count(process_set)
    if k == 0:
        raise RuntimeError(
            "alltoall(splits=...) called on a process owning no member "
            "device of the process set (in the reference's per-rank model "
            "a non-member never calls the op)")
    datas, rsplits = alltoallv([data] * k, [sp] * k, name=name,
                               process_set=process_set)
    return datas[0], rsplits[0]


def _local_member_positions(ps) -> List[int]:
    """Positions within the set (0..size-1) of this process's local ranks,
    in the same order their rows appear in rank-stacked eager arrays."""
    mesh = ps.flat_mesh()
    me = jax.process_index()
    if not _is_multiprocess(mesh):
        return list(range(int(mesh.devices.size)))
    return [i for i, d in enumerate(mesh.devices.flat)
            if d.process_index == me]


def barrier(*, process_set=None) -> None:
    """Block until every member device reaches the barrier."""
    ps = _ps.get_process_set(process_set)
    ones = replicated_stack(np.ones((1,), np.int32), ps)
    _, jmeta, _mask = _join_sync(ps, "barrier", ones, "barrier")
    out = _run("barrier", ones, "barrier", ps,
               lambda t: _ops.barrier(axes=(HVD_AXIS,)) * t, "barrier",
               publish_meta=jmeta)
    with _stall.watched("barrier"):
        from ..elastic import chaos as _chaos
        _chaos.raise_if_armed()  # injected at=sync comm fault
        jax.block_until_ready(out)


def join() -> int:
    """``hvd.join()`` (reference JoinOp, SURVEY.md 3.2).

    Multi-process mode: this process stops contributing and DRAINS -- it
    keeps participating in the survivors' collectives with identity
    payloads (zeros / +-inf / ones) until every process has joined, then
    returns the last rank to join.  Ranks with fewer batches can therefore
    stop early while the rest keep allreducing, without deadlock.

    Single-controller SPMD mode: every rank executes every step by
    construction, so there are no stragglers; join degenerates to a
    barrier and returns -1 ("no rank joined last"), the reference's
    convention when ranks are indistinguishable.
    """
    from . import joinop as _join
    flush_deferred()
    ps = _ps.get_process_set(None)
    mesh = ps.flat_mesh()
    if not _is_multiprocess(mesh) or _join.client() is None:
        barrier()
        return -1
    return _join.join_drain(mesh)
