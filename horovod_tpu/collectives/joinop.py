"""JoinOp: straggler draining for the multi-process eager path.

Reference semantics (``horovod/common/ops/operations.cc`` JoinOp, SURVEY.md
section 3.2): a rank that runs out of batches calls ``hvd.join()`` and
stops contributing, while the remaining ranks keep issuing collectives;
the joined rank keeps PARTICIPATING (with identity payloads) so nobody
deadlocks, and ``join`` returns once every rank has joined, yielding the
last rank to join.

The reference implements this inside its controller negotiation: joined
ranks answer every negotiation round with a Join request and the
coordinator fabricates their contribution.  Here there is no negotiation
-- multi-process eager collectives are SPMD programs spanning every
process's devices -- so the draining protocol runs over the JAX
coordination service instead:

* every multi-process eager dispatch first runs a fixed tiny "presence"
  collective (a psum of one-hot rows) telling everyone which ranks are
  still active;
* when anyone has joined, the active caller publishes the op's replay
  metadata (kind, shape, dtype, op params) to the coordination KV store
  under the op's fence sequence number;
* each joined process sits in :func:`join_drain`, running the same
  presence rounds, fetching the metadata, and re-issuing the identical
  collective through the public eager API with an identity payload
  (zeros for sums/gathers, +/-inf for min/max, ones for products);
* ``Average`` reductions are rescaled by ``n_ranks / n_active`` so the
  mean is taken over the ranks that actually contributed (reference
  behavior); integer-dtype Average during a join phase is unsupported
  (the truncating-int rescale is ill-defined; gradients are floats);
* a ragged :func:`~horovod_tpu.collectives.eager.allgatherv` from a
  joined rank naturally contributes ZERO rows (its size row replays as
  0), exactly the reference's zero-size gather contribution.

The presence round costs one scalar-sized collective per eager dispatch;
the multi-process eager path is already serialized per dispatch (see
``eager._run``), so this changes constants, not shape.  The in-step
(traced, fused) path -- the performance path -- is untouched: under SPMD
a traced step executes on every device by construction, so there are no
stragglers to drain.
"""

from __future__ import annotations

import contextlib
import json
import threading
from typing import Optional

import jax
import numpy as np

from ..core import process_sets as _ps
from ..core.config import _env_bool, _env_int
from ..parallel.mesh import HVD_AXIS

_lock = threading.Lock()
_gen = 0              # completed join cycles (namespaces the KV keys)
_joined = False       # this process is currently inside join_drain
_replaying = False    # this process is re-issuing a fetched op
_presence_cache = {}  # mesh -> compiled presence program
_presence_idx = 0     # presence rounds completed this generation
_flush_state = None   # active batched flush: {"mask", "remaining"}


def reset() -> None:
    """Forget join state (``hvd.shutdown()``): a re-initialized world
    starts at generation 0 with nobody joined.

    Also clears THIS process's ``draining/`` flag from the coordination
    store (a stale flag would make every later multi-process subset
    collective raise a spurious "drained in hvd.join" error).  Broader
    records (``last/``, ``op/``) are deliberately left alone: a recursive
    delete here races against slower processes still reading them at
    program exit (measured: rank 0 mid-``_read_last`` timed out after a
    faster rank's shutdown wiped the store).  Stale non-flag records only
    matter to a world that re-initializes against the SAME coordination
    service after using ``hvd.join()`` -- the elastic flow rebuilds the
    service (new port) every epoch, so this is a documented limitation of
    user-owned same-service re-init, not a reachable path of ours.
    """
    global _gen, _joined, _replaying, _presence_idx, _flush_state
    cl = client()
    if cl is not None:
        try:
            cl.key_value_delete(_drain_key(jax.process_index()))
        except Exception:  # pragma: no cover - old client / no such key
            pass
    with _lock:
        _gen = 0
        _joined = False
        _replaying = False
        _presence_idx = 0
        _flush_state = None
        _presence_cache.clear()


def client():
    return getattr(jax._src.distributed.global_state, "client", None)


def _op_key(seq: int) -> str:
    return f"hvd_join/{_gen}/op/{seq}"


def _last_prefix() -> str:
    return f"hvd_join/{_gen}/last/"


def _last_fallback_key() -> str:
    return f"hvd_join/{_gen}/last_fallback"


def _flush_key(presence_idx: int) -> str:
    return f"hvd_join/{_gen}/flush/{presence_idx}"


def _drain_prefix() -> str:
    return f"hvd_join/{_gen}/draining/"


def _drain_key(proc: int) -> str:
    return f"{_drain_prefix()}{proc}"


def _kv_int(v) -> int:
    """KV values come back as str or bytes depending on jaxlib."""
    return int(v.decode() if isinstance(v, bytes) else v)


def _draining_procs() -> list:
    """Processes currently inside :func:`join_drain` (best effort).

    Read from the coordination KV store; empty when the client lacks
    ``key_value_dir_get`` (old jaxlib) -- the check then degrades to the
    pre-round-3 silent behavior.
    """
    cl = client()
    dir_get = getattr(cl, "key_value_dir_get", None)
    if dir_get is None:  # pragma: no cover - old jaxlib
        return []
    try:
        return [_kv_int(v) for _k, v in dir_get(_drain_prefix())]
    except Exception:  # pragma: no cover - store raced with _gen bump
        return []


def _timeout_ms() -> int:
    # NOTE: _env_int prepends the HOROVOD_/HVD_TPU_ prefix itself.
    return _env_int("JOIN_TIMEOUT", 60) * 1000


def _presence_program(mesh):
    if mesh not in _presence_cache:
        def spmd(block):  # block: [1, n] this device's row
            return jax.lax.psum(block[0], HVD_AXIS)[None]
        _presence_cache[mesh] = jax.jit(jax.shard_map(
            spmd, mesh=mesh,
            in_specs=jax.sharding.PartitionSpec(HVD_AXIS),
            out_specs=jax.sharding.PartitionSpec(HVD_AXIS)))
    return _presence_cache[mesh]


def presence_round(mesh, active: bool) -> np.ndarray:
    """One presence collective: returns the [n] 0/1 mask of active ranks.

    Every process with devices in ``mesh`` must run this the same number
    of times (actives once per eager dispatch, joined once per drain-loop
    iteration) -- it is itself a collective.
    """
    from . import eager

    global _presence_idx
    n = int(mesh.devices.size)
    positions = eager._local_member_positions(_ps.get_process_set(None))
    rows = np.zeros((len(positions), n), np.int32)
    if active:
        for i, g in enumerate(positions):
            rows[i, g] = 1
    arr = eager._to_global(rows, mesh)
    out = _presence_program(mesh)(arr)
    jax.block_until_ready(out)
    eager._coordination_fence(mesh)
    # Rounds pair 1:1 across processes (they are collectives), so this
    # counter agrees everywhere -- it keys the flush-size records.
    _presence_idx += 1
    return eager.one_row(out)


def _applies(ps) -> bool:
    """Join handling applies: active multi-process global-set dispatch
    with a coordination service and the protocol not disabled."""
    from . import eager

    if _replaying or _joined or _env_bool("JOIN_DISABLE"):
        return False
    if client() is None or not ps.is_global():
        return False
    return eager._is_multiprocess(ps.flat_mesh())


def _publish_flush_size(mask: np.ndarray, size: int, n_ranks: int) -> None:
    """After a presence round that found drained ranks, tell them how
    many ops to replay before their next presence round.  Keyed by the
    just-completed round's index; every active publishes the same value
    (SPMD), overwrite benign."""
    if int(mask.sum()) < n_ranks:
        client().key_value_set(_flush_key(_presence_idx - 1), str(size),
                               allow_overwrite=True)


@contextlib.contextmanager
def flush(ps, n_ops: int):
    """Batch ``n_ops`` consecutive global-set eager collectives behind ONE
    presence round (round-2 verdict weak #2: the per-dispatch presence
    collective + fence doubled the eager control-plane latency).

    Inside the context, :func:`sync` returns the cached mask instead of
    running a round; drained ranks read the published flush size and
    replay exactly ``n_ops`` collectives before their next presence
    round.  The caller MUST issue exactly ``n_ops`` global-set
    collectives inside the context -- more raises here, and an exception
    (or under-issue) with slots still pending publishes an abort record
    at the next slot so drained ranks fail fast instead of blocking
    until HOROVOD_JOIN_TIMEOUT.  Used by the grouped/fused eager entry
    points, whose op count is known up front -- including the fused
    deferred flush, where ``n_ops`` is the number of dispatch UNITS
    (fused buckets + per-op fallbacks), not the number of pending
    handles: drained ranks replay one collective per unit, with fused
    buckets carrying their layout in the published metadata.
    """
    global _flush_state
    from . import eager
    eager.flush_deferred()  # pending async ops dispatch before this batch
    ps_ = _ps.get_process_set(ps)
    if _flush_state is not None or n_ops <= 1 or not _applies(ps_):
        yield
        return
    mesh = ps_.flat_mesh()
    mask = presence_round(mesh, active=True)
    _publish_flush_size(mask, n_ops, ps_.size())
    _flush_state = {"mask": mask, "remaining": n_ops}
    draining = int(mask.sum()) < ps_.size()

    def _abort_pending(message: str) -> None:
        # Drained ranks are blocked on the NEXT op slot; an abort there
        # makes them raise cleanly (slots after it are never read -- the
        # drained loop stops at the first abort).
        publish(mesh, {"kind": "abort", "message": message})

    try:
        yield
    except BaseException as e:
        if draining and _flush_state["remaining"] > 0:
            _abort_pending(f"{type(e).__name__}: {e}")
        raise
    finally:
        remaining = _flush_state["remaining"]
        _flush_state = None
    if remaining > 0 and draining:
        _abort_pending(f"flush under-issued: {n_ops - remaining}/{n_ops}")
        raise RuntimeError(
            f"join flush published {n_ops} ops but only "
            f"{n_ops - remaining} were issued; drained ranks would block "
            f"on the missing replays")


def sync(ps) -> Optional[np.ndarray]:
    """Called at the top of every public eager collective.

    Returns ``None`` when no join handling applies (single process, no
    coordination service, non-global process set, or this call is itself
    a drain replay); otherwise runs a presence round -- or consumes the
    enclosing :func:`flush` context's cached mask -- and returns the
    [n] 0/1 mask of active ranks.
    """
    global _flush_state
    from . import eager

    if _flush_state is not None and _applies(ps):
        st = _flush_state
        if st["remaining"] <= 0:
            raise RuntimeError(
                "more global-set collectives issued inside a join flush "
                "than its declared op count")
        st["remaining"] -= 1
        return st["mask"].copy()
    if _replaying or _joined:
        return None
    if _env_bool("JOIN_DISABLE"):
        # Opt-out for workloads that never call hvd.join(): skips the
        # per-dispatch presence collective + its fence on the eager
        # multi-process hot path (measured: see docs/benchmarks.md
        # "Eager control plane").  join() raises under this flag.
        return None
    if client() is None:
        return None
    if not ps.is_global():
        # Join draining runs on the GLOBAL set only (reference restricts
        # Join the same way).  A multi-process SUBSET collective issued
        # while some member process is drained would deadlock: the drained
        # process sits in a global-mesh presence psum, the survivors wait
        # on the member-only sub-mesh program.  Fail loudly instead
        # (best-effort: a process entering join_drain concurrently with
        # this check can still slip through and hit HOROVOD_JOIN_TIMEOUT).
        mesh = ps.flat_mesh()
        if eager._is_multiprocess(mesh):
            members = {d.process_index for d in mesh.devices.flat}
            draining = sorted(members.intersection(_draining_procs()))
            if draining:
                raise RuntimeError(
                    f"eager collective on process set {ps.name!r} while "
                    f"member process(es) {draining} are drained in "
                    f"hvd.join(): join draining only covers the global "
                    f"process set; finish the join before issuing subset "
                    f"collectives")
        return None
    mesh = ps.flat_mesh()
    if not eager._is_multiprocess(mesh):
        return None
    mask = presence_round(mesh, active=True)
    _publish_flush_size(mask, 1, ps.size())
    return mask


def publish(mesh, meta: dict) -> None:
    """Publish an op's replay metadata at its fence sequence number.

    EVERY active process publishes (SPMD -- they all dispatch the same op
    with identical metadata), so overwriting is expected and benign.
    """
    from . import eager

    procs = tuple(sorted({d.process_index for d in mesh.devices.flat}))
    seq = eager._peek_next_seq(procs)
    client().key_value_set(_op_key(seq), json.dumps(meta),
                           allow_overwrite=True)


def identity_value(op_value: str, dtype):
    """The reduction identity a joined rank contributes."""
    if op_value == "min":
        return float(np.inf) if np.issubdtype(dtype, np.floating) \
            else np.iinfo(dtype).max
    if op_value == "max":
        return float(-np.inf) if np.issubdtype(dtype, np.floating) \
            else np.iinfo(dtype).min
    if op_value == "product":
        return 1
    return 0  # sum / average / adasum / gathers / scatters


def _replay(meta: dict) -> None:
    """Re-issue the published collective with an identity payload."""
    global _replaying
    from . import eager
    from .reduce_op import ReduceOp

    # Derived from the namespace, not hand-listed: publish serializes ANY
    # compression.__name__, so a codec added to Compression must replay.
    # resolve_compressor_name additionally re-derives parameterized codecs
    # (PowerSGD<r>/TopK<f>) whose factory never ran on this drained rank.
    from .compression import resolve_compressor_name
    kind = meta["kind"]
    name = meta.get("name")
    _replaying = True
    try:
        if kind == "abort":
            # An active rank hit an error AFTER its presence round (e.g.
            # broadcast from a joined root): it published this instead of
            # op metadata so drained ranks fail cleanly rather than
            # blocking on a collective that will never be dispatched.
            raise RuntimeError(
                f"collective aborted during join phase: {meta['message']}")
        if kind == "barrier":
            eager.barrier()
            return
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        k_local = eager.local_rank_count(None)
        row = shape[1:]
        if kind == "allreduce":
            # Fused deferred-flush buckets replay through this same
            # branch: the published shape IS the fused [n, sum(widths)]
            # layout, so re-issuing it reproduces the active ranks'
            # bucket collective bitwise.  Like the codecs, the layout is
            # derived from the metadata rather than hand-listed -- the
            # widths ride along purely as a cross-check against a
            # corrupt/raced record (their sum must equal the row size).
            widths = meta.get("fused_widths")
            if widths is not None and tuple(row) != (int(sum(widths)),):
                raise RuntimeError(
                    f"fused replay metadata is inconsistent: bucket shape "
                    f"{tuple(meta['shape'])} does not match widths "
                    f"{widths} (sum {int(sum(widths))})")
            comp = resolve_compressor_name(meta["compression"])
            fwidths = meta.get("factor_widths")
            if fwidths is not None:
                # Low-rank replay cross-check: the widths the active side
                # will exchange must match what this rank re-derives from
                # shape + codec rank, or the traced factor programs
                # diverge and the psum wedges.
                from .compression import (powersgd_factor_widths,
                                          is_powersgd)
                if not is_powersgd(comp):
                    raise RuntimeError(
                        f"replay metadata carries factor_widths but codec "
                        f"{meta['compression']!r} is not a low-rank codec")
                size = max(int(np.prod(row, dtype=np.int64)), 1)
                expect = list(powersgd_factor_widths(size, comp.rank))
                if list(fwidths) != expect:
                    raise RuntimeError(
                        f"low-rank replay metadata is inconsistent: "
                        f"published factor widths {list(fwidths)} != "
                        f"{expect} derived from shape {tuple(meta['shape'])} "
                        f"and rank {comp.rank}")
            fill = identity_value(meta["op"], dtype)
            x = np.full((k_local,) + row, fill, dtype)
            eager.allreduce(x, ReduceOp(meta["op"]), name=name,
                            prescale_factor=meta["pre"],
                            postscale_factor=meta["post"],
                            compression=comp)
        elif kind == "broadcast":
            eager.broadcast(np.zeros((k_local,) + row, dtype),
                            meta["root"], name=name)
        elif kind == "allgather":
            eager.allgather(np.zeros((k_local,) + row, dtype), name=name)
        elif kind == "reducescatter":
            # Identity payload, like the allreduce branch: zeros corrupt
            # min/max/product reductions.
            fill = identity_value(meta["op"], dtype)
            eager.reducescatter(np.full((k_local,) + row, fill, dtype),
                                ReduceOp(meta["op"]), name=name,
                                _join_k=meta.get("jk"))
        elif kind == "alltoall":
            eager.alltoall(np.zeros((k_local,) + row, dtype), name=name)
        else:  # pragma: no cover - forward compat
            raise RuntimeError(f"unknown join replay kind {kind!r}")
    finally:
        _replaying = False


def join_drain(mesh) -> int:
    """The joined-rank loop: mirror every active dispatch with an identity
    replay until everyone has joined; returns the last rank to join."""
    global _gen, _joined, _presence_idx
    from . import eager

    if _env_bool("JOIN_DISABLE"):
        raise RuntimeError(
            "hvd.join() requires the presence protocol, but "
            "HOROVOD_JOIN_DISABLE=1 turned it off")

    cl = client()
    positions = eager._local_member_positions(_ps.get_process_set(None))
    procs = tuple(sorted({d.process_index for d in mesh.devices.flat}))
    # Record WHEN this process joined: the fence sequence the next
    # collective would use.  Two processes joining between the same pair
    # of presence rounds get the same seq; the tie breaks on rank, so
    # every reader resolves the same "last rank to join" (reference
    # controller behavior).  A process's ranks join together; report its
    # highest.  Every write happens before its writer's first inactive
    # presence round, so all writes are visible once the mask drains to
    # zero.
    join_seq = eager._peek_next_seq(procs)
    cl.key_value_set(f"{_last_prefix()}{join_seq:012d}_{positions[-1]:012d}",
                     str(positions[-1]), allow_overwrite=True)
    # Old-jaxlib fallback (no key_value_dir_get): a single overwritten
    # key -- last-writer-wins, the pre-round-3 nondeterministic-on-ties
    # behavior, better than failing the join outright.
    cl.key_value_set(_last_fallback_key(), str(positions[-1]),
                     allow_overwrite=True)
    cl.key_value_set(_drain_key(jax.process_index()),
                     str(jax.process_index()), allow_overwrite=True)
    _joined = True
    try:
        while True:
            mask = presence_round(mesh, active=False)
            if int(mask.sum()) == 0:
                break
            # The actives published how many collectives this presence
            # round covers (1 for singles, the bucket count for batched
            # flushes); replay exactly that many before the next round.
            m = _kv_int(cl.blocking_key_value_get(
                _flush_key(_presence_idx - 1), _timeout_ms()))
            for _ in range(m):
                seq = eager._peek_next_seq(procs)
                raw = cl.blocking_key_value_get(_op_key(seq), _timeout_ms())
                _replay(json.loads(raw))
    finally:
        _joined = False
        # An exception exit (abort replay, KV timeout) leaves _gen
        # un-bumped: clear the drain flag so a survived error does not
        # make every later subset collective raise "drained in hvd.join".
        try:
            cl.key_value_delete(_drain_key(jax.process_index()))
        except Exception:  # pragma: no cover - old client / already gone
            pass
    last = _read_last(cl)
    with _lock:
        _gen += 1
        _presence_idx = 0  # flush keys are namespaced per generation
    return last


def _read_last(cl) -> int:
    """Deterministic "last rank to join": max (join_seq, rank) over every
    joiner's record.  Keys are fixed-width so the lexicographic max IS the
    numeric max; falls back to the single last-writer-wins key when dir
    listing is unavailable (old jaxlib)."""
    dir_get = getattr(cl, "key_value_dir_get", None)
    if dir_get is not None:
        entries = dir_get(_last_prefix())
        if entries:
            _k, v = max(entries, key=lambda kv: kv[0])
            return _kv_int(v)
    return _kv_int(cl.blocking_key_value_get(_last_fallback_key(),
                                             _timeout_ms()))
