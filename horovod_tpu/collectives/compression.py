"""Gradient compression (``hvd.Compression`` parity).

Reference: ``horovod/torch/compression.py`` -- ``Compression.none`` and
``Compression.fp16`` cast the tensor down before the allreduce and back up
after.  On TPU the natural low-precision wire format is bfloat16 (same
exponent range as fp32 -- no loss scaling needed, and the MXU/ICI path is
optimized for it), so ``bf16`` is provided alongside ``fp16``; both halve
bytes-on-the-wire for fp32 gradients.

The cast is emitted inside the traced step, so XLA fuses it with the
fusion-buffer pack and the collective kernel -- the "compression" costs no
extra HBM round trip.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Compress/decompress around a collective."""

    @staticmethod
    def compress(tensor):
        """Return (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None  # set by subclasses

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and \
                jnp.dtype(dtype).itemsize > jnp.dtype(cls.wire_dtype).itemsize:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Compression:
    """Namespace matching ``hvd.Compression.{none,fp16}`` plus TPU ``bf16``."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
