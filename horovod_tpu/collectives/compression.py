"""Gradient compression (``hvd.Compression`` parity).

Reference: ``horovod/torch/compression.py`` -- ``Compression.none`` and
``Compression.fp16`` cast the tensor down before the allreduce and back up
after.  On TPU the natural low-precision wire format is bfloat16 (same
exponent range as fp32 -- no loss scaling needed, and the MXU/ICI path is
optimized for it), so ``bf16`` is provided alongside ``fp16``; both halve
bytes-on-the-wire for fp32 gradients.

``fp8`` (e4m3 + per-bucket scale factors) quarters the wire bytes of fp32
gradients.  Unlike the cast codecs it cannot ride a plain ``psum`` (XLA
reduces in the wire dtype: 3 mantissa bits of ACCUMULATION error and
overflow at ~448), so the collective layer swaps the exchange itself:
``ops.fp8_allreduce`` (alltoall shards -> f32 local reduce -> async-capable
all_gather) for Sum/Average, and per-exchange quantization of the VHDD
``ppermute`` payloads for Adasum -- all arithmetic stays f32 on-chip, fp8
touches only the wire.  Scales ride as one f32 scalar per shard
(negligible).  Quantization noise is ~2^-4 relative per direction (e4m3
rounding); parity tests bound it.

The casts/quantizations are emitted inside the traced step, so XLA fuses
them with the fusion-buffer pack and the collective kernel -- the
"compression" costs no extra HBM round trip.
"""

from __future__ import annotations

import jax.numpy as jnp

E4M3_MAX = 448.0
_SCALE_FLOOR = 1e-30


def fp8_quantize(x, axis=None):
    """Quantize to e4m3 with a max-abs scale (per tensor, or per row of
    ``axis=1``-style leading dim when ``axis`` is given).

    Returns ``(q, scale)``: ``x ~= q.astype(f32) * scale``.
    """
    x32 = x.astype(jnp.float32)
    if axis is None:
        absmax = jnp.max(jnp.abs(x32))
    else:
        red = tuple(i for i in range(x32.ndim) if i != axis)
        absmax = jnp.max(jnp.abs(x32), axis=red, keepdims=False)
    scale = jnp.maximum(absmax / E4M3_MAX, _SCALE_FLOOR)
    if axis is None:
        q = (x32 / scale).astype(jnp.float8_e4m3fn)
    else:
        shape = [1] * x32.ndim
        shape[axis] = -1
        q = (x32 / scale.reshape(shape)).astype(jnp.float8_e4m3fn)
    return q, scale


def fp8_dequantize(q, scale, dtype):
    return (q.astype(jnp.float32) * scale).astype(dtype)


class Compressor:
    """Compress/decompress around a collective."""

    @staticmethod
    def compress(tensor):
        """Return (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None  # set by subclasses

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and \
                jnp.dtype(dtype).itemsize > jnp.dtype(cls.wire_dtype).itemsize:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class FP8Compressor(Compressor):
    """e4m3 wire with per-bucket scales -- an EXCHANGE-level codec.

    ``compress``/``decompress`` are identities: fp8 cannot ride a plain
    psum (see module docstring), so the collective layer recognises
    ``wire_format == "fp8_e4m3"`` and swaps the exchange itself
    (``ops.fp8_allreduce`` for Sum/Average; quantized VHDD permutes for
    Adasum).  Surfaces that cannot swap the exchange raise rather than
    silently sum in fp8.
    """
    wire_format = "fp8_e4m3"

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


def is_fp8(compression) -> bool:
    return getattr(compression, "wire_format", "").startswith("fp8")


class Compression:
    """Namespace matching ``hvd.Compression.{none,fp16}`` plus TPU ``bf16``
    and ``fp8`` (e4m3, per-bucket scales)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    fp8 = FP8Compressor
