"""Gradient compression (``hvd.Compression`` parity).

Reference: ``horovod/torch/compression.py`` -- ``Compression.none`` and
``Compression.fp16`` cast the tensor down before the allreduce and back up
after.  On TPU the natural low-precision wire format is bfloat16 (same
exponent range as fp32 -- no loss scaling needed, and the MXU/ICI path is
optimized for it), so ``bf16`` is provided alongside ``fp16``; both halve
bytes-on-the-wire for fp32 gradients.

``fp8`` (e4m3 + per-bucket scale factors) quarters the wire bytes of fp32
gradients.  Unlike the cast codecs it cannot ride a plain ``psum`` (XLA
reduces in the wire dtype: 3 mantissa bits of ACCUMULATION error and
overflow at ~448), so the collective layer swaps the exchange itself:
``ops.fp8_allreduce`` (alltoall shards -> f32 local reduce -> async-capable
all_gather) for Sum/Average, and per-exchange quantization of the VHDD
``ppermute`` payloads for Adasum -- all arithmetic stays f32 on-chip, fp8
touches only the wire.  Scales ride as one f32 scalar per shard
(negligible).  Quantization noise is ~2^-4 relative per direction (e4m3
rounding); parity tests bound it.

The casts/quantizations are emitted inside the traced step, so XLA fuses
them with the fusion-buffer pack and the collective kernel -- the
"compression" costs no extra HBM round trip.
"""

from __future__ import annotations

import math
import re
from typing import Tuple

import jax.numpy as jnp

E4M3_MAX = 448.0
_SCALE_FLOOR = 1e-30


def fp8_quantize(x, axis=None):
    """Quantize to e4m3 with a max-abs scale (per tensor, or per row of
    ``axis=1``-style leading dim when ``axis`` is given).

    Returns ``(q, scale)``: ``x ~= q.astype(f32) * scale``.
    """
    x32 = x.astype(jnp.float32)
    # ``initial=0.0`` guards degenerate reductions: a zero-size axis has
    # nothing to reduce over (jnp.max would raise), and an all-zero row
    # must land on absmax == 0, not garbage.
    if axis is None:
        absmax = jnp.max(jnp.abs(x32), initial=0.0)
    else:
        red = tuple(i for i in range(x32.ndim) if i != axis)
        absmax = jnp.max(jnp.abs(x32), axis=red, keepdims=False, initial=0.0)
    # All-zero (or empty) rows use scale 1.0 so quantize and dequantize
    # both produce EXACT zeros; _SCALE_FLOOR only backstops nonzero rows
    # whose absmax underflows the division.
    scale = jnp.where(absmax > 0.0,
                      jnp.maximum(absmax / E4M3_MAX, _SCALE_FLOOR),
                      jnp.ones_like(absmax))
    if axis is None:
        q = (x32 / scale).astype(jnp.float8_e4m3fn)
    else:
        shape = [1] * x32.ndim
        shape[axis] = -1
        q = (x32 / scale.reshape(shape)).astype(jnp.float8_e4m3fn)
    return q, scale


def fp8_dequantize(q, scale, dtype):
    return (q.astype(jnp.float32) * scale).astype(dtype)


class Compressor:
    """Compress/decompress around a collective."""

    @staticmethod
    def compress(tensor):
        """Return (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None  # set by subclasses

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and \
                jnp.dtype(dtype).itemsize > jnp.dtype(cls.wire_dtype).itemsize:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class FP8Compressor(Compressor):
    """e4m3 wire with per-bucket scales -- an EXCHANGE-level codec.

    ``compress``/``decompress`` are identities: fp8 cannot ride a plain
    psum (see module docstring), so the collective layer recognises
    ``wire_format == "fp8_e4m3"`` and swaps the exchange itself
    (``ops.fp8_allreduce`` for Sum/Average; quantized VHDD permutes for
    Adasum).  Surfaces that cannot swap the exchange raise rather than
    silently sum in fp8.
    """
    wire_format = "fp8_e4m3"

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


def is_fp8(compression) -> bool:
    return getattr(compression, "wire_format", "").startswith("fp8")


class _ErrorFeedbackCompressor(Compressor):
    """Base for the error-feedback EXCHANGE-level codecs (PowerSGD / top-k).

    Like :class:`FP8Compressor`, ``compress``/``decompress`` are identities:
    the codec cannot ride a plain psum, so the collective layer recognises
    ``wire_format`` and swaps the exchange (``ops.powersgd_allreduce`` /
    ``ops.topk_allreduce``).  Unlike fp8, the exchange is LOSSY in a way that
    biases training unless the per-rank compression error is fed back into
    the next step's gradient -- ``DistributedOptimizer`` threads that
    residual through the optimizer state (see ``optim/distributed.py``).
    """
    wire_format = ""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


def is_powersgd(compression) -> bool:
    return getattr(compression, "wire_format", "") == "powersgd"


def is_topk(compression) -> bool:
    return getattr(compression, "wire_format", "") == "topk"


class _HierLegCompressor(Compressor):
    """Per-leg EXCHANGE-level codec for the two-level (DCN x ICI) path.

    Carries one codec per hop: ``ici`` rides the fast intra-slice legs
    (reduce-scatter + allgather), ``dcn`` only the slow cross-slice hop.
    ``compress``/``decompress`` are identities -- like fp8, the collective
    layer recognises ``wire_format == "hier_legs"`` and swaps the exchange
    for ``ops.hierarchical_allreduce`` with the legs' codecs applied
    inside.  The ICI leg must stay psum-compatible (none/fp16/bf16); the
    DCN leg may additionally be fp8 or an error-feedback codec
    (powersgd/topk), whose residual then lives in the DCN-shard domain.
    """
    wire_format = "hier_legs"
    ici = NoneCompressor
    dcn = NoneCompressor

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


def is_hier_legs(compression) -> bool:
    return getattr(compression, "wire_format", "") == "hier_legs"


def hier_leg_compressor(ici, dcn):
    """Memoized per-leg codec class (see :class:`_HierLegCompressor`).

    Registered on :class:`Compression` under its ``__name__`` like the
    parameterized codecs, so join replay resolves it by name.
    """
    ici = parse_compression(ici)
    dcn = parse_compression(dcn)
    if is_hier_legs(ici) or is_hier_legs(dcn):
        raise ValueError("per-leg codecs do not nest")
    if getattr(ici, "wire_format", ""):
        raise ValueError(
            f"ICI leg codec must be psum-compatible (none|fp16|bf16), "
            f"got {ici.__name__}")
    name = f"Hier{ici.__name__}Dcn{dcn.__name__}"
    cls = getattr(Compression, name, None)
    if cls is None:
        cls = type(name, (_HierLegCompressor,), {"ici": ici, "dcn": dcn})
        setattr(Compression, name, cls)
    return cls


def is_error_feedback(compression) -> bool:
    """True for codecs whose exchange needs error-feedback residual state.
    A per-leg codec is error-feedback iff its DCN leg is."""
    if is_hier_legs(compression):
        return is_error_feedback(compression.dcn)
    return is_powersgd(compression) or is_topk(compression)


def _fraction_token(fraction: float) -> str:
    # "0.01" -> "0p01", "1e-05" -> "1em05": keeps the class name a valid
    # identifier while staying invertible for join replay on drained ranks.
    return ("%g" % fraction).replace(".", "p").replace("-", "m")


def _parse_fraction_token(token: str) -> float:
    return float(token.replace("p", ".").replace("m", "-"))


def powersgd_compressor(rank: int):
    """Memoized rank-``r`` PowerSGD codec class (Vogels et al., 2019).

    The class is registered as an attribute of :class:`Compression` under
    its ``__name__`` so the join-replay codec lookup (``joinop._replay``)
    resolves it by name like the builtin codecs.
    """
    rank = int(rank)
    if rank < 1:
        raise ValueError(f"powersgd rank must be >= 1, got {rank}")
    name = f"PowerSGD{rank}Compressor"
    cls = getattr(Compression, name, None)
    if cls is None:
        cls = type(name, (_ErrorFeedbackCompressor,),
                   {"wire_format": "powersgd", "rank": rank})
        setattr(Compression, name, cls)
    return cls


def topk_compressor(fraction: float):
    """Memoized top-``fraction`` magnitude-sparsification codec (DGC-style,
    Lin et al., 2018).  Registered on :class:`Compression` like
    :func:`powersgd_compressor`."""
    fraction = float(fraction)
    if not 0.0 < fraction <= 1.0:
        raise ValueError(
            f"topk fraction must be in (0, 1], got {fraction}")
    name = f"TopK{_fraction_token(fraction)}Compressor"
    cls = getattr(Compression, name, None)
    if cls is None:
        cls = type(name, (_ErrorFeedbackCompressor,),
                   {"wire_format": "topk", "fraction": fraction})
        setattr(Compression, name, cls)
    return cls


def resolve_compressor_name(name: str):
    """Codec class from its ``__name__`` -- the join-replay lookup.

    Builtin and already-instantiated parameterized codecs come straight off
    the :class:`Compression` namespace; a parameterized name that was never
    constructed in THIS process (a drained rank replaying a peer's deferred
    op) is re-derived from the encoded parameters.
    """
    for c in vars(Compression).values():
        if isinstance(c, type) and c.__name__ == name:
            return c
    m = re.fullmatch(r"PowerSGD(\d+)Compressor", name)
    if m:
        return powersgd_compressor(int(m.group(1)))
    m = re.fullmatch(r"TopK(.+)Compressor", name)
    if m:
        return topk_compressor(_parse_fraction_token(m.group(1)))
    m = re.fullmatch(r"Hier(.+?)Dcn(.+)", name)
    if m:
        return hier_leg_compressor(resolve_compressor_name(m.group(1)),
                                   resolve_compressor_name(m.group(2)))
    raise KeyError(f"unknown compressor {name!r}")


def parse_compression(spec):
    """``HOROVOD_COMPRESSION`` spec -> codec class.

    Accepts ``none``/``fp16``/``bf16``/``fp8``, ``powersgd:<rank>`` and
    ``topk:<fraction>``; a codec class passes through unchanged.  A
    per-leg spec names a codec per hop of the two-level exchange, e.g.
    ``ici:none,dcn:fp8`` (omitted legs default to ``none``).
    """
    if spec is None:
        return Compression.none
    if isinstance(spec, type):
        return spec
    s = str(spec).strip().lower()
    if "ici:" in s or "dcn:" in s:
        legs = {}
        for part in s.split(","):
            leg, sep, sub = part.strip().partition(":")
            if leg not in ("ici", "dcn") or not sep:
                raise ValueError(
                    f"bad per-leg compression spec {spec!r}: expected "
                    f"comma-separated ici:<codec>,dcn:<codec> entries")
            if leg in legs:
                raise ValueError(
                    f"bad per-leg compression spec {spec!r}: duplicate "
                    f"{leg} leg")
            legs[leg] = sub
        return hier_leg_compressor(legs.get("ici", "none"),
                                   legs.get("dcn", "none"))
    plain = {"none": Compression.none, "fp16": Compression.fp16,
             "bf16": Compression.bf16, "fp8": Compression.fp8}
    if s in plain:
        return plain[s]
    kind, sep, arg = s.partition(":")
    if sep:
        try:
            if kind == "powersgd":
                return powersgd_compressor(int(arg))
            if kind == "topk":
                return topk_compressor(float(arg))
        except ValueError as e:
            raise ValueError(f"bad compression spec {spec!r}: {e}") from None
    raise ValueError(
        f"bad compression spec {spec!r}: expected none|fp16|bf16|fp8|"
        f"powersgd:<rank>|topk:<fraction>|ici:<codec>,dcn:<codec>")


def powersgd_matrix_shape(size: int) -> Tuple[int, int]:
    """Near-square matricization of a flat bucket: ``m = ceil(sqrt(size))``
    rows, ``c = ceil(size / m)`` cols (zero-padded to ``m * c``).  Shared by
    the exchange, the wire accounting, and the join-replay width check."""
    size = int(size)
    if size < 1:
        raise ValueError(f"bucket size must be >= 1, got {size}")
    m = int(math.ceil(math.sqrt(size)))
    c = int(math.ceil(size / m))
    return m, c


def powersgd_effective_rank(size: int, rank: int) -> int:
    m, c = powersgd_matrix_shape(size)
    return max(1, min(int(rank), m, c))


def powersgd_factor_widths(size: int, rank: int) -> Tuple[int, int]:
    """Flat widths of the (P, Q) factors a rank-``rank`` exchange puts on
    the wire for a ``size``-element bucket: ``(r_eff * m, r_eff * c)``."""
    m, c = powersgd_matrix_shape(size)
    r = max(1, min(int(rank), m, c))
    return r * m, r * c


def topk_count(size: int, fraction: float) -> int:
    """Number of (value, index) pairs a top-``fraction`` exchange keeps."""
    return max(1, int(math.ceil(int(size) * float(fraction))))


def wire_payload_bytes(compression, size: int,
                       itemsize: int = 4, world: int = 1) -> int:
    """Estimated allreduce-equivalent on-wire payload for one exchange of a
    ``size``-element bucket (used by the ``compression_ratio`` timeline
    counter and the bench wire accounting; link-bytes scaling by
    ``(n-1)/n`` cancels in ratios so it is left out).

    - dtype codecs: the full bucket at the wire itemsize;
    - fp8: one byte per element (per-shard scales are negligible);
    - powersgd: the P and Q factor allreduces -- ``r*m + r*c`` f32
      elements total;
    - topk: ``k`` f32 values + ``k`` int32 indices allgathered -- an
      allgather moves half the link bytes of an allreduce of the same
      payload, so it counts at half weight.
    """
    size = int(size)
    if size < 1:
        return 0
    if is_hier_legs(compression):
        # ``world`` carries the ICI extent here: the RS/AG legs move the
        # full bucket at the ICI codec's wire width, the DCN hop only a
        # 1/n_ici shard at the DCN codec's width.
        n_ici = max(int(world), 1)
        shard = max(1, (size + n_ici - 1) // n_ici)
        return (wire_payload_bytes(compression.ici, size, itemsize)
                + wire_payload_bytes(compression.dcn, shard, itemsize))
    if is_powersgd(compression):
        pw, qw = powersgd_factor_widths(size, compression.rank)
        return 4 * (pw + qw)
    if is_topk(compression):
        k = topk_count(size, compression.fraction)
        return 8 * k // 2
    if is_fp8(compression):
        return size
    wire_itemsize = itemsize
    wd = getattr(compression, "wire_dtype", None)
    if wd is not None:
        wire_itemsize = min(itemsize, jnp.dtype(wd).itemsize)
    return size * wire_itemsize


class Compression:
    """Namespace matching ``hvd.Compression.{none,fp16}`` plus TPU ``bf16``,
    ``fp8`` (e4m3, per-bucket scales), and the error-feedback exchange
    codecs ``powersgd(rank)`` / ``topk(fraction)`` (parameterized factories;
    instantiated classes are registered here by name for join replay)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    fp8 = FP8Compressor
    powersgd = staticmethod(powersgd_compressor)
    topk = staticmethod(topk_compressor)
    hier = staticmethod(hier_leg_compressor)
