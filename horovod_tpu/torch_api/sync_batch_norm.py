"""SyncBatchNorm for the torch shim (``horovod/torch/sync_batch_norm.py``
parity).

BatchNorm whose batch statistics are computed over the GLOBAL batch: each
rank contributes its local sum / sum-of-squares / count through a Sum
allreduce on the XLA mesh, and the backward pass likewise sum-reduces the
two gradient statistics, so training with sync BN is numerically identical
to single-process training on the concatenated batch.

Weight/bias gradients are returned as LOCAL sums (like every other layer),
so the wrapping ``DistributedOptimizer`` averages them -- matching the
reference's division of labour.
"""

from __future__ import annotations

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from ..collectives.reduce_op import Sum
from . import allreduce, size


class SyncBatchNorm(_BatchNorm):
    """Drop-in ``hvd.SyncBatchNorm(num_features, ...)``.

    In eval mode (or when no peer exists) it behaves exactly like the
    underlying ``_BatchNorm``; in training mode the statistics cross the
    mesh.  ``process_set`` restricts the stat exchange to a subset of
    ranks (e.g. per model-parallel group).
    """

    def __init__(self, *args, process_set=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._process_set = process_set

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(f"expected at least 2D input, got "
                             f"{input.dim()}D")

    def forward(self, input: torch.Tensor) -> torch.Tensor:
        self._check_input_dim(input)
        if not self.training or size() == 1:
            return super().forward(input)

        out, mean, var_biased, count = _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.eps, self._process_set)

        if self.track_running_stats:
            with torch.no_grad():
                self.num_batches_tracked += 1
                momentum = (1.0 / float(self.num_batches_tracked)
                            if self.momentum is None else self.momentum)
                n = float(count)
                var_unbiased = var_biased * n / max(n - 1.0, 1.0)
                self.running_mean.mul_(1 - momentum).add_(
                    momentum * mean.detach())
                self.running_var.mul_(1 - momentum).add_(
                    momentum * var_unbiased.detach())
        return out


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, input, weight, bias, eps, process_set):
        dims = [0] + list(range(2, input.dim()))
        c = input.shape[1]
        local_count = float(input.numel()) / c
        stats = torch.cat([
            input.sum(dims),
            (input * input).sum(dims),
            torch.full((1,), local_count, dtype=input.dtype),
        ])
        g = allreduce(stats, op=Sum, name="sync_batch_norm.fwd",
                      process_set=process_set)
        g_count = float(g[-1])
        g_sum, g_sqsum = g[:c], g[c:2 * c]
        mean = g_sum / g_count
        var = g_sqsum / g_count - mean * mean
        invstd = torch.rsqrt(var + eps)

        shape = [1, c] + [1] * (input.dim() - 2)
        xhat = (input - mean.view(shape)) * invstd.view(shape)
        ctx.affine = weight is not None
        w = weight if ctx.affine else torch.ones(c, dtype=input.dtype)
        b = bias if bias is not None else torch.zeros(c, dtype=input.dtype)
        out = xhat * w.view(shape) + b.view(shape)
        ctx.save_for_backward(xhat, w, invstd)
        ctx.g_count = g_count
        ctx.process_set = process_set
        return out, mean, var, torch.tensor(g_count)

    @staticmethod
    def backward(ctx, grad_out, _gm, _gv, _gc):
        xhat, weight, invstd = ctx.saved_tensors
        dims = [0] + list(range(2, grad_out.dim()))
        c = grad_out.shape[1]
        shape = [1, c] + [1] * (grad_out.dim() - 2)

        sum_dy_local = grad_out.sum(dims)
        sum_dy_xhat_local = (grad_out * xhat).sum(dims)
        g = allreduce(torch.cat([sum_dy_local, sum_dy_xhat_local]), op=Sum,
                      name="sync_batch_norm.bwd", process_set=ctx.process_set)
        sum_dy, sum_dy_xhat = g[:c], g[c:]

        n = ctx.g_count
        grad_input = (weight * invstd).view(shape) / n * (
            n * grad_out - sum_dy.view(shape)
            - xhat * sum_dy_xhat.view(shape))
        # Local sums: the DistributedOptimizer averages these like any
        # other parameter gradient.
        grad_weight = sum_dy_xhat_local if ctx.affine else None
        grad_bias = sum_dy_local if ctx.affine else None
        return grad_input, grad_weight, grad_bias, None, None
