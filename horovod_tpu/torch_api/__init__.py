"""``horovod_tpu.torch``: drop-in ``horovod.torch`` API over the TPU core.

Parity surface (reference ``horovod/torch/__init__.py`` + ``mpi_ops.py`` +
``optimizer.py`` + ``functions.py``): ``init/rank/size/...``, tensor
collectives with async handles (``allreduce[_async][_]``, ``allgather``,
``broadcast``, ``alltoall``, ``grouped_allreduce``, ``synchronize``,
``poll``), ``DistributedOptimizer`` with per-gradient hooks and
``backward_passes_per_step``, ``broadcast_parameters`` /
``broadcast_optimizer_state``, and ``Compression``.

Execution model: torch stays the user-facing autograd/optimizer engine on
host CPU; every collective stages the tensor to the XLA mesh through the
eager path (``torch -> numpy -> jax -> numpy -> torch``, zero-copy on the
torch side) and is asynchronous exactly like the reference's enqueue --
JAX's async dispatch replaces the background thread, and the handle table
replaces ``HandleManager`` (``horovod/torch/handle_manager.cc``).

One controller process == one Horovod rank (launch with
``python -m horovod_tpu.run -np N``); a single process with multiple local
devices treats each device as a rank for the collective math, matching the
core's semantics.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np
import torch

from ..core.basics import (  # noqa: F401
    init, shutdown, is_initialized, size, rank, local_size, local_rank,
    cross_size, cross_rank, is_homogeneous, nccl_built, mpi_built,
    cuda_built, rocm_built, start_timeline, stop_timeline,
    gloo_built, tpu_built, mpi_threads_supported,
)
from ..core.exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt,
)
from ..core.process_sets import (  # noqa: F401
    ProcessSet, add_process_set, remove_process_set, get_process_set,
)
from ..collectives.reduce_op import (  # noqa: F401
    ReduceOp, Average, Sum, Min, Max, Product, Adasum,
)
from ..collectives.compression import Compression  # noqa: F401
# HOROVOD_STEPS_PER_EXEC pickup: torch stays a host-side autograd engine,
# so there is no scan loop to compile into -- but torch training scripts
# use the same knob to size their inner step loop between fences/logging
# (and the cycle scheduler batches that window's collectives), keeping the
# env contract uniform across the keras/torch/native frontends.
from ..training import steps_per_execution  # noqa: F401
from . import elastic_state as elastic  # noqa: F401  (hvd.elastic.TorchState)
# Make `import horovod_tpu.torch.elastic` work as a module path too (the
# file is elastic_state.py; register the reference-style names under both
# the real package and the `horovod_tpu.torch` alias).
import sys as _sys
_sys.modules[__name__ + ".elastic"] = elastic
_sys.modules["horovod_tpu.torch.elastic"] = elastic
from ..collectives import eager as _eager


def _to_stack(t: torch.Tensor) -> np.ndarray:
    return _eager.replicated_stack(t.detach().cpu().numpy())


def _from_row(out, like: torch.Tensor) -> torch.Tensor:
    if isinstance(out, np.ndarray):       # host-fetched (grouped to_host)
        row = out[0].copy()
    else:
        # one_row copies: the buffer is jax-owned (and may be
        # non-writable).
        row = _eager.one_row(out)
    try:
        res = torch.from_numpy(row)
    except TypeError:  # torch-unsupported wire dtype (ml_dtypes bfloat16)
        res = torch.from_numpy(row.astype(np.float32))
    return res.to(like.dtype)


def _wire_stage(stacks: List[np.ndarray], compression):
    """Cast float32 stacks to the compression's wire dtype ON HOST.

    The eager ``Compression`` classes cast inside the traced program --
    after the full-precision buffer already crossed host->device.  For the
    torch shim that staging link (PCIe on a real host; a ~10 MiB/s pooled
    tunnel here) dominates the collective cost, so halving the bytes
    before staging is the single biggest lever.  The reduction then runs
    in the wire dtype, exactly the reference's compress -> allreduce(fp16)
    -> decompress pipeline; ``_from_row`` upcasts on the way back.
    """
    import jax.numpy as jnp
    wire = {"FP16Compressor": np.float16,
            "BF16Compressor": jnp.bfloat16}.get(
                getattr(compression, "__name__", ""))
    if wire is None or any(s.dtype != np.float32 for s in stacks):
        return stacks, compression
    return [s.astype(wire) for s in stacks], Compression.none


# -- tensor collectives ------------------------------------------------------

def allreduce(tensor: torch.Tensor, average: Optional[bool] = None,
              name: Optional[str] = None, compression=Compression.none,
              op: Optional[ReduceOp] = None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              process_set=None) -> torch.Tensor:
    op = _resolve_op(average, op)
    stacks, compression = _wire_stage([_to_stack(tensor)], compression)
    out = _eager.allreduce(stacks[0], op, name=name,
                           process_set=process_set,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor,
                           compression=compression)
    return _from_row(out, tensor)


def allreduce_(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    result = allreduce(tensor, **kwargs)
    tensor.copy_(result)
    return tensor


def allreduce_async(tensor: torch.Tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[ReduceOp] = None,
                    compression=Compression.none, process_set=None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0) -> int:
    op = _resolve_op(average, op)
    stacks, compression = _wire_stage([_to_stack(tensor)], compression)
    # allreduce_async defers in multi-process join mode (one presence
    # round covers every op enqueued before the next synchronize) and
    # dispatches immediately elsewhere.
    h = _eager.allreduce_async(stacks[0], op, name=name,
                               process_set=process_set,
                               compression=compression,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor)
    return _handles.adopt(h, tensor, inplace=False)


def allreduce_async_(tensor: torch.Tensor, **kwargs) -> int:
    h = allreduce_async(tensor, **kwargs)
    _handles.mark_inplace(h)
    return h


def grouped_allreduce(tensors: List[torch.Tensor], average=None, name=None,
                      op=None, process_set=None,
                      compression=Compression.none,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0) -> List[torch.Tensor]:
    op = _resolve_op(average, op)
    stacks, compression = _wire_stage([_to_stack(t) for t in tensors],
                                      compression)
    outs = _eager.grouped_allreduce(stacks, op,
                                    name=name, process_set=process_set,
                                    compression=compression, to_host=True,
                                    prescale_factor=prescale_factor,
                                    postscale_factor=postscale_factor)
    return [_from_row(o, t) for o, t in zip(outs, tensors)]


def grouped_allreduce_async(tensors: List[torch.Tensor], average=None,
                            name=None, op=None, process_set=None,
                            compression=Compression.none,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0) -> int:
    """One handle for the whole group (``hvd.grouped_allreduce_async``
    parity); ``synchronize(handle)`` returns the list of results."""
    op = _resolve_op(average, op)
    stacks, compression = _wire_stage([_to_stack(t) for t in tensors],
                                      compression)
    # Async contract: dispatch now (device arrays, non-blocking), fetch
    # ONCE per bucket at synchronize() via the assemble hook.
    reds, spec = _eager._grouped_allreduce_buckets(
        stacks, op, name=name, process_set=process_set,
        compression=compression, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor)
    return _handles.alloc(
        reds, list(tensors), inplace=False,
        assemble=lambda r: _eager._unfuse_buckets(r, spec, to_host=True))


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None,
                    process_set=None) -> int:
    """Like the sync :func:`allgather`, first dims MAY differ across
    ranks; the ragged size negotiation is host-synchronous, so the handle
    completes immediately (upstream's contract only promises a handle)."""
    result = allgather(tensor, name=name, process_set=process_set)
    return _handles.alloc_custom(lambda: result)


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None, process_set=None) -> int:
    out = _eager.broadcast(_to_stack(tensor), root_rank, name=name,
                           process_set=process_set)
    return _handles.alloc(out, tensor, inplace=False)


def broadcast_async_(tensor: torch.Tensor, root_rank: int, **kwargs) -> int:
    h = broadcast_async(tensor, root_rank, **kwargs)
    _handles.mark_inplace(h)
    return h


def reducescatter_async(tensor: torch.Tensor, op: ReduceOp = Average,
                        name: Optional[str] = None, process_set=None) -> int:
    out = _eager.reducescatter(_to_stack(tensor), op, name=name,
                               process_set=process_set)
    return _handles.alloc(out, tensor, inplace=False)


def alltoall_async(tensor: torch.Tensor,
                   splits: Optional[torch.Tensor] = None,
                   name: Optional[str] = None, process_set=None) -> int:
    """With ``splits`` the ragged negotiation is host-synchronous (sizes
    must be exchanged to shape the result), so the handle completes
    immediately -- upstream's contract only promises a handle."""
    if splits is None:
        out = _eager.alltoall(_to_stack(tensor), name=name,
                              process_set=process_set)
        return _handles.alloc(out, tensor, inplace=False)
    result = alltoall(tensor, splits, name=name, process_set=process_set)
    return _handles.alloc_custom(lambda: result)


def grouped_allreduce_async_(tensors: List[torch.Tensor], **kwargs) -> int:
    h = grouped_allreduce_async(tensors, **kwargs)
    _handles.mark_inplace(h)
    return h


def grouped_allgather(tensors: List[torch.Tensor], name=None,
                      process_set=None) -> List[torch.Tensor]:
    """Reference ``hvd.grouped_allgather``: one fused gather."""
    outs = _eager.grouped_allgather([_to_stack(t) for t in tensors],
                                    name=name, process_set=process_set)
    return [_from_row(o, t) for o, t in zip(outs, tensors)]


def grouped_reducescatter(tensors: List[torch.Tensor], op: ReduceOp = Average,
                          name=None, process_set=None) -> List[torch.Tensor]:
    """Reference ``hvd.grouped_reducescatter``: one fused scatter."""
    outs = _eager.grouped_reducescatter([_to_stack(t) for t in tensors], op,
                                        name=name, process_set=process_set)
    return [_from_row(o, t) for o, t in zip(outs, tensors)]


def sparse_allreduce_async(tensor: torch.Tensor,
                           name: Optional[str] = None,
                           op: ReduceOp = Average,
                           process_set=None):
    """Allreduce a ``torch.sparse_coo`` tensor WITHOUT densifying
    (reference ``horovod/torch/mpi_ops.py::sparse_allreduce_async``):
    each rank's indices+values are allgathered (ragged) and summed by
    coalescing, so the wire cost scales with nnz, not the dense shape.
    Returns a handle; ``synchronize(handle)`` yields the coalesced
    sparse result.

    Dispatch note: the ragged gather's size exchange is synchronous on
    the calling thread (only the host-side assembly is deferred to
    ``synchronize``), so unlike the dense ``*_async`` ops this one does
    not overlap with subsequent enqueues.
    """
    if not tensor.is_sparse:
        raise ValueError("sparse_allreduce_async expects a sparse tensor; "
                         "use allreduce for dense tensors")
    if op not in (Average, Sum):
        raise ValueError("sparse allreduce supports Average/Sum only")
    t = tensor.detach().cpu().coalesce()
    sd = t.sparse_dim()
    tail = tuple(t.values().shape[1:])
    width = sd + int(np.prod(tail, dtype=np.int64))  # prod(()) == 1
    # One ragged row per nonzero: [index dims..., value elements...] in
    # f64 (exact for int32 indices and f32 values on the wire).
    if t._nnz():
        payload = np.concatenate(
            [t.indices().numpy().T.astype(np.float64),
             t.values().numpy().reshape(t._nnz(), -1).astype(np.float64)],
            axis=1)
    else:
        payload = np.zeros((0, width), np.float64)
    gathered = _eager.allgather_value(payload, name=name,
                                      process_set=process_set)
    world = get_process_set(process_set).size()

    def assemble():
        g = np.asarray(gathered)
        idx = torch.as_tensor(g[:, :sd].T.copy(), dtype=torch.long)
        vals = torch.as_tensor(g[:, sd:].copy(), dtype=torch.float64)
        vals = vals.reshape((len(g),) + tail)
        # coalesce() sums duplicate coordinates (the reduction itself) in
        # f64; Average divides the SUM, and the cast back to the input
        # dtype comes last -- same order as the dense path, so integer
        # averages truncate toward zero identically.
        summed = torch.sparse_coo_tensor(idx, vals,
                                         tensor.shape).coalesce()
        values = summed.values() / world if op is Average \
            else summed.values()
        return torch.sparse_coo_tensor(summed.indices(),
                                       values.to(tensor.dtype),
                                       tensor.shape).coalesce()

    return _handles.alloc_custom(assemble)


def allgather(tensor: torch.Tensor, name: Optional[str] = None,
              process_set=None) -> torch.Tensor:
    """Reference parity: first dimensions MAY differ across ranks (the
    reference's negotiation exchanges sizes; here the ragged-capable
    allgatherv path does the same size exchange)."""
    out = _eager.allgather_value(tensor.detach().cpu().numpy(),
                                 name=name, process_set=process_set)
    # out is a fresh process-owned ndarray (np.concatenate result): no
    # defensive copy needed.
    return torch.from_numpy(out).to(tensor.dtype)


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None, process_set=None) -> torch.Tensor:
    out = _eager.broadcast(_to_stack(tensor), root_rank, name=name,
                           process_set=process_set)
    return _from_row(out, tensor)


def broadcast_(tensor: torch.Tensor, root_rank: int, **kwargs):
    tensor.copy_(broadcast(tensor, root_rank, **kwargs))
    return tensor


def alltoall(tensor: torch.Tensor, splits: Optional[torch.Tensor] = None,
             name: Optional[str] = None, process_set=None):
    """Reference parity (``horovod.torch.alltoall``): with ``splits`` the
    exchange is uneven -- ``splits[i]`` rows of ``tensor`` go to rank
    ``i`` -- and the result is ``(received, received_splits)``; without,
    ``tensor`` splits evenly and only the received tensor returns."""
    if splits is None:
        out = _eager.alltoall(_to_stack(tensor), name=name,
                              process_set=process_set)
        return _from_row(out, tensor)
    sp = splits.detach().cpu().numpy() if isinstance(splits, torch.Tensor) \
        else splits
    data, rsplits = _eager.alltoallv_row(
        tensor.detach().cpu().numpy(), sp, name=name,
        process_set=process_set)
    return (torch.from_numpy(data.copy()).to(tensor.dtype),
            torch.from_numpy(rsplits.astype(np.int64)))


def reducescatter(tensor: torch.Tensor, op: ReduceOp = Average,
                  name: Optional[str] = None,
                  process_set=None) -> torch.Tensor:
    out = _eager.reducescatter(_to_stack(tensor), op, name=name,
                               process_set=process_set)
    return _from_row(out, tensor)


def barrier(process_set=None) -> None:
    _eager.barrier(process_set=process_set)


def join(device=None) -> int:
    return _eager.join()


def _resolve_op(average: Optional[bool], op: Optional[ReduceOp]) -> ReduceOp:
    if op is not None and average is not None:
        raise ValueError("specify either op or average, not both")
    if op is not None:
        return op
    if average is False:
        return Sum
    return Average


# -- handle table ------------------------------------------------------------

class _HandleTable:
    """HandleManager analogue for the torch surface."""

    def __init__(self):
        # (out, like, inplace, assemble) -- see alloc().
        self._entries: Dict[int, Tuple[Any, Any, bool, Any]] = {}

    def alloc(self, out, like: torch.Tensor, inplace: bool,
              assemble=None) -> int:
        """``assemble``: optional post-synchronize hook mapping the raw
        stored value (e.g. fused bucket device arrays) to the per-tensor
        results -- lets grouped async ops defer the device->host fetch to
        synchronize() while staying truly asynchronous."""
        h = _eager._alloc_handle(out)
        self._entries[h] = (out, like, inplace, assemble)
        return h

    def alloc_custom(self, assemble) -> int:
        """Handle whose synchronize() returns ``assemble()`` (used by
        sparse allreduce, whose result is built host-side)."""
        h = _eager._alloc_handle(np.zeros(()))  # done-immediately marker
        self._entries[h] = (assemble, None, False, None)
        return h

    def adopt(self, h: int, like: torch.Tensor, inplace: bool = False,
              assemble=None) -> int:
        """Register torch-side bookkeeping for an EXISTING eager handle
        (one whose dispatch may be deferred -- see eager.allreduce_async);
        synchronize() resolves it through the eager table."""
        self._entries[h] = (None, like, inplace, assemble)
        return h

    def mark_inplace(self, h: int) -> None:
        out, like, _, assemble = self._entries[h]
        self._entries[h] = (out, like, True, assemble)

    def synchronize(self, h: int) -> "torch.Tensor | List[torch.Tensor]":
        out, like, inplace, assemble = self._entries[h]
        # _eager.synchronize consumes the eager entry on success AND on a
        # handle-bound (deferred-flush) error; drop the torch entry in
        # lockstep so the tables never desynchronize -- a retry of a
        # consumed handle is a KeyError on both sides, and the original
        # error raised exactly once.
        try:
            result = _eager.synchronize(h)
        finally:
            self._entries.pop(h, None)
        if like is None and callable(out):  # custom (sparse) handle
            return out()
        if assemble is not None:
            result = assemble(result)
        if isinstance(like, (list, tuple)):  # grouped handle
            values = [_from_row(r, t) for r, t in zip(result, like)]
            if inplace:
                for t, v in zip(like, values):
                    t.copy_(v)
                return list(like)
            return values
        value = _from_row(result, like)
        if inplace:
            like.copy_(value)
            return like
        return value

    def poll(self, h: int) -> bool:
        return _eager.poll(h)


_handles = _HandleTable()


def synchronize(handle: int) -> "torch.Tensor | List[torch.Tensor]":
    """Single-tensor handles return the tensor; grouped handles (from
    ``grouped_allreduce_async[_]``) return the list of results."""
    return _handles.synchronize(handle)


def poll(handle: int) -> bool:
    return _handles.poll(handle)


# -- parameter/optimizer broadcast ------------------------------------------

def broadcast_parameters(params, root_rank: int = 0,
                         process_set=None) -> None:
    """In-place broadcast of a ``state_dict`` or ``named_parameters``.

    Tensors are FUSED per dtype into one flat buffer and broadcast with a
    single collective per dtype (the fusion-buffer idiom): a per-tensor
    loop would compile one XLA program per distinct shape -- ~50 programs
    for a ResNet-50, minutes of compile time on the tunnelled TPU before
    the first step runs.
    """
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = sorted(params)
    tensors = [p.data if p.requires_grad else p
               for _, p in items if isinstance(p, torch.Tensor)]
    rows = _eager.broadcast_fused(
        [t.detach().cpu().numpy() for t in tensors], root_rank,
        name="broadcast.params", process_set=process_set)
    for t, row in zip(tensors, rows):
        t.copy_(torch.from_numpy(row).to(t.dtype))


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0, process_set=None) -> None:
    """Broadcast optimizer hyperparameters and per-param state tensors."""
    from ..optim.functions import broadcast_object
    state = optimizer.state_dict()

    def enc(obj):
        if isinstance(obj, torch.Tensor):
            return obj.cpu().numpy()
        if isinstance(obj, dict):
            return {k: enc(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [enc(v) for v in obj]
        return obj

    def dec(obj):
        if isinstance(obj, np.ndarray):
            return torch.from_numpy(obj.copy())
        if isinstance(obj, dict):
            return {k: dec(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [dec(v) for v in obj]
        return obj

    synced = broadcast_object(enc(state), root_rank, process_set=process_set)
    optimizer.load_state_dict(dec(synced))


def broadcast_object(obj, root_rank: int = 0, name=None, process_set=None):
    from ..optim.functions import broadcast_object as _bo
    return _bo(obj, root_rank, process_set=process_set)


def allgather_object(obj, name=None, process_set=None) -> list:
    """Rank-ordered list of every rank's object (reference
    ``horovod/torch/functions.py::allgather_object``)."""
    from ..optim.functions import allgather_object as _ago
    return _ago(obj, name=name, process_set=process_set)


from .optimizer import DistributedOptimizer  # noqa: E402,F401
from .sync_batch_norm import SyncBatchNorm  # noqa: E402,F401
