"""``horovod_tpu.torch.DistributedOptimizer``: hook-based gradient sync.

Parity with ``horovod/torch/optimizer.py::_DistributedOptimizer``: wraps a
``torch.optim.Optimizer``; an autograd hook per parameter enqueues an
asynchronous allreduce the moment its gradient is produced (overlap with
the rest of backward), ``synchronize()`` drains the handles before
``step()``, and ``backward_passes_per_step`` accumulates locally between
syncs.  The enqueue lands on the XLA mesh via the eager collective path
instead of a background NCCL thread, and the dynamic-subclass technique
(instance ``__class__`` rebound to ``(_Mixin, OriginalOptimizer)``)
preserves the wrapped optimizer's ``step``/``state_dict`` behavior.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import torch

from ..collectives.compression import Compression
from ..collectives.reduce_op import Average, ReduceOp
from . import _handles, allreduce_async_
from . import batching as _batching


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin providing hooks + synchronize; never instantiated directly."""

    def _init_distributed(self, named_parameters, compression, op,
                          backward_passes_per_step, process_set,
                          sparse_as_dense,
                          gradient_predivide_factor: float = 1.0) -> None:
        self._sparse_as_dense = sparse_as_dense
        # Reference semantics: with op=Average, split the averaging --
        # grads scale by 1/factor BEFORE the reduction and factor/size
        # after, controlling where the division's rounding lands (fp16
        # ranges).  Rides the collective stack's prescale/postscale
        # support, which composes correctly with process-set sizes and
        # join-phase active-rank rescaling (op stays Average).
        f = float(gradient_predivide_factor)
        self._prescale = 1.0 / f
        self._postscale = f
        # Every param needs a UNIQUE name: in multi-process mode the
        # native scheduler cuts fused buckets in name-sorted order, so
        # duplicate names would let bucket layouts diverge across ranks
        # and sum mismatched gradients (the reference likewise rejects
        # dup/incomplete named_parameters, horovod/torch/optimizer.py).
        self._param_names = {
            v: f"allreduce.noname.{i}.{j}"
            for i, group in enumerate(self.param_groups)
            for j, v in enumerate(group["params"])}
        if named_parameters:
            named = list(named_parameters)
            names = [k for k, _ in named]
            if len(set(names)) != len(names):
                dups = sorted({n for n in names if names.count(n) > 1})
                raise ValueError(
                    f"named_parameters contains duplicate names: {dups}")
            self._param_names.update({v: k for k, v in named})
        self._compression = compression
        self._op = op
        self._process_set = process_set
        self.backward_passes_per_step = backward_passes_per_step
        self._counter: Dict[torch.Tensor, int] = {}
        self._pending: Dict[torch.Tensor, int] = {}
        self._grad_accs = []
        self._should_synchronize = True
        self._register_hooks()

    # -- hooks ------------------------------------------------------------
    def _register_hooks(self) -> None:
        for group in self.param_groups:
            for p in group["params"]:
                if not p.requires_grad:
                    continue
                if p.grad is None:
                    p.grad = p.data.new_zeros(p.shape)
                # Hook the grad accumulator so it fires once per backward,
                # after autograd finished accumulating into p.grad (same
                # trick as the reference's _make_hook).
                tmp = p.expand_as(p)
                acc = tmp.grad_fn.next_functions[0][0]
                acc.register_hook(self._make_hook(p))
                self._grad_accs.append(acc)

    def _make_hook(self, p: torch.Tensor):
        def hook(*ignore):
            if p in self._pending:
                raise AssertionError(
                    "gradient produced twice without synchronize(); call "
                    "optimizer.synchronize() (or step()) every "
                    "backward_passes_per_step backwards")
            self._counter[p] = self._counter.get(p, 0) + 1
            if self._counter[p] < self.backward_passes_per_step:
                return  # local accumulation pass: no comm
            self._counter[p] = 0
            if p.grad.is_sparse:
                # Reference parity (horovod/torch/optimizer.py
                # sparse_as_dense): dense allreduce after densify, or an
                # explicit error -- never a silent wrong result.  NOTE:
                # a .grad object is only sparse when autograd CREATED it
                # (after zero_grad(set_to_none=True), the torch default);
                # while the wrap-time dense zero buffer is alive, sparse
                # outputs accumulate into it and reduce densely, so the
                # strict error surfaces at the first post-zero_grad
                # backward, not step 1.
                if not self._sparse_as_dense:
                    raise ValueError(
                        "sparse gradient encountered (e.g. Embedding("
                        "sparse=True)); pass sparse_as_dense=True to "
                        "DistributedOptimizer to densify before the "
                        "collective")
                p.grad = p.grad.to_dense()
            if self.backward_passes_per_step > 1:
                p.grad.div_(self.backward_passes_per_step)
            name = self._param_names.get(p)
            if name is None:
                raise AssertionError(
                    "parameter was added to the optimizer after "
                    "DistributedOptimizer() wrapped it; re-wrap so every "
                    "parameter has a stable unique allreduce name")
            # Hot path: hand the gradient to the native cycle scheduler,
            # which fuses everything produced within HOROVOD_CYCLE_TIME
            # into one collective per bucket (RunLoopOnce parity).  The
            # per-tensor eager dispatch is the no-native fallback.
            b = _batching.batcher()
            if b is not None:
                self._pending[p] = ("native", b.enqueue(
                    p.grad, name, self._op, self._compression,
                    self._process_set, self._prescale, self._postscale))
            else:
                self._pending[p] = ("eager", allreduce_async_(
                    p.grad, op=self._op, name=name,
                    compression=self._compression,
                    process_set=self._process_set,
                    prescale_factor=self._prescale,
                    postscale_factor=self._postscale))
        return hook

    # -- sync -------------------------------------------------------------
    def synchronize(self) -> None:
        """Drain outstanding allreduce handles (grads updated in place).

        Drains EVERY pending handle even when one fails: aborting at the
        first error would leave later params' handles pending forever
        (their flush already consumed them), so every later ``step()``
        would retry dead handles and raise KeyError over the real error.
        The first error is re-raised once the table is empty.
        """
        first_error = None
        for p, (kind, h) in list(self._pending.items()):
            try:
                if kind == "native":
                    _batching.batcher().wait(h)
                else:
                    _handles.synchronize(h)
            except Exception as e:
                if first_error is None:
                    first_error = e
            finally:
                del self._pending[p]
        if first_error is not None:
            raise first_error

    class _DisableSync:
        def __init__(self, opt):
            self._opt = opt

        def __enter__(self):
            self._opt._should_synchronize = False

        def __exit__(self, *args):
            self._opt._should_synchronize = True

    def skip_synchronize(self):
        """Context manager: tell ``step()`` synchronize() already ran."""
        return self._DisableSync(self)

    def step(self, closure=None):
        if self._should_synchronize:
            self.synchronize()
        return super().step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._pending:
            raise AssertionError(
                "zero_grad() called with pending allreduce handles; call "
                "synchronize() or step() first")
        return super().zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[Iterable] = None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = Average,
                         gradient_predivide_factor: float = 1.0,
                         num_groups: int = 0,
                         process_set=None,
                         sparse_as_dense: bool = False
                         ) -> torch.optim.Optimizer:
    """Wrap a torch optimizer so ``step()`` sees globally-reduced grads.

    ``num_groups`` is accepted for reference signature parity and has no
    effect: bucketing here is byte-threshold driven by the native cycle
    scheduler (``HOROVOD_FUSION_THRESHOLD``), the knob upstream's group
    count approximates.
    """
    # Validate BEFORE mutating the instance: rebinding __class__ and then
    # raising would leave the caller's optimizer half-initialized.
    if gradient_predivide_factor != 1.0 and op is not Average:
        raise ValueError("gradient_predivide_factor requires op=Average "
                         "(reference behavior)")
    if gradient_predivide_factor <= 0.0:
        raise ValueError("gradient_predivide_factor must be positive, got "
                         f"{gradient_predivide_factor}")
    named = list(named_parameters) if named_parameters is not None else None
    optimizer.__class__ = type(
        "Distributed" + optimizer.__class__.__name__,
        (_DistributedOptimizer, optimizer.__class__), {})
    optimizer._init_distributed(named, compression, op,
                                backward_passes_per_step, process_set,
                                sparse_as_dense,
                                gradient_predivide_factor)
    return optimizer
