"""``horovod_tpu.torch.elastic``: TorchState + the elastic run decorator.

Parity with ``horovod/torch/elastic/state.py::TorchState``: registers a
``torch.nn.Module`` and/or ``torch.optim.Optimizer`` plus arbitrary
scalars; ``commit()`` snapshots their ``state_dict()`` into host memory,
``restore()`` rolls back, and ``sync()`` broadcasts rank 0's copy so
restarted/rescaled workers adopt the survivors' progress.  The broadcast
rides the XLA collective plane (tensors via ``broadcast_parameters``-
style leaf broadcast, everything else pickled).
"""

from __future__ import annotations

import copy
from typing import Any, Dict

import numpy as np
import torch

from ..elastic.run_loop import run  # noqa: F401  (hvd.elastic.run parity)
from ..elastic.sampler import ElasticSampler  # noqa: F401
from ..elastic.state import ObjectState, State


def _broadcast_state_dict(sd: Dict[str, Any], root_rank: int = 0):
    """Broadcast a (possibly nested) state_dict: tensor leaves through the
    collective plane, the rest by pickle."""
    from ..optim.functions import broadcast_, broadcast_object

    tensors = {k: v for k, v in sd.items() if torch.is_tensor(v)}
    rest = {k: v for k, v in sd.items() if not torch.is_tensor(v)}
    out = dict(broadcast_object(rest, root_rank=root_rank))
    if tensors:
        names = sorted(tensors)

        # numpy cannot represent these; upcast losslessly for the wire
        # (the receive side casts back).  getattr: float8 dtypes only
        # exist in torch >= 2.1.
        no_numpy = tuple(
            dt for dt in (torch.bfloat16,
                          getattr(torch, "float8_e4m3fn", None),
                          getattr(torch, "float8_e5m2", None))
            if dt is not None)

        def to_np(t):
            t = t.detach().cpu()
            if t.dtype in no_numpy:
                t = t.to(torch.float32)
            return t.numpy()

        synced = broadcast_({k: to_np(tensors[k]) for k in names},
                            root_rank=root_rank)
        for k in names:
            t = torch.as_tensor(np.asarray(synced[k]))
            out[k] = t.to(tensors[k].dtype)
    return out


class TorchState(State):
    """Elastic state for torch model/optimizer (+ scalar attributes)::

        state = hvd.elastic.TorchState(model=model, optimizer=opt,
                                       batch=0, epoch=0)
    """

    def __init__(self, model: torch.nn.Module = None, optimizer=None,
                 **kwargs):
        super().__init__()
        self.model = model
        self.optimizer = optimizer
        self._scalars = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._saved: Dict[str, Any] = {}
        self.commit()

    def _snapshot(self) -> Dict[str, Any]:
        snap: Dict[str, Any] = {"scalars": {
            k: copy.deepcopy(getattr(self, k)) for k in self._scalars}}
        if self.model is not None:
            snap["model"] = {k: v.detach().cpu().clone() if
                             torch.is_tensor(v) else copy.deepcopy(v)
                             for k, v in self.model.state_dict().items()}
        if self.optimizer is not None:
            snap["optimizer"] = copy.deepcopy(self.optimizer.state_dict())
        return snap

    def commit(self) -> None:
        self._check_desync({
            "model": self.model.state_dict() if self.model is not None
            else {},
            "scalars": {k: getattr(self, k) for k in self._scalars}})
        self._saved = self._snapshot()
        self._check_host_updates()

    def restore(self) -> None:
        if self.model is not None and "model" in self._saved:
            self.model.load_state_dict(self._saved["model"])
        if self.optimizer is not None and "optimizer" in self._saved:
            self.optimizer.load_state_dict(self._saved["optimizer"])
        for k, v in self._saved.get("scalars", {}).items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        from ..optim.functions import broadcast_object

        if self.model is not None:
            self.model.load_state_dict(
                _broadcast_state_dict(self.model.state_dict()))
        if self.optimizer is not None:
            self.optimizer.load_state_dict(
                broadcast_object(self.optimizer.state_dict(), root_rank=0))
        scalars = broadcast_object(
            {k: getattr(self, k) for k in self._scalars}, root_rank=0)
        for k, v in scalars.items():
            setattr(self, k, v)
        self.commit()
