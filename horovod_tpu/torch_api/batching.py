"""Native cycle-time gradient micro-batching for the torch shim.

The reference's hot path (``horovod/common/operations.cc::RunLoopOnce``):
framework hooks enqueue gradients to a C++ queue; a background thread wakes
every ``HOROVOD_CYCLE_TIME`` ms, fuses whatever is ready (up to
``HOROVOD_FUSION_THRESHOLD`` bytes per bucket), and runs ONE collective per
bucket.  Without this, the eager torch path dispatches one XLA program per
gradient -- exactly the per-tensor launch overhead the fusion buffer
exists to kill.

This module wires the native C++ scheduler (``horovod_tpu._core``) into the
torch ``DistributedOptimizer``: hooks enqueue (tensor, handle) payloads;
the native cycle thread groups them by (dtype, op, compression,
process-set) and its callback dispatches a single fused
``grouped_allreduce`` per group, copies results into the grads in place,
and completes the native handles.  ``synchronize`` = flush + wait.

Falls back transparently when the native lib can't build
(``HVD_TPU_NATIVE_CORE=0`` or no compiler): callers check
:func:`batcher` for None.
"""

from __future__ import annotations

import atexit
import threading
from typing import Dict, List, Optional, Tuple

import torch

from .. import _core
from ..core.exceptions import HorovodInternalError
from ..core.state import global_state

_lock = threading.Lock()
_batcher: Optional["GradBatcher"] = None


class GradBatcher:
    def __init__(self, cycle_ms: float, fusion_bytes: int,
                 stall_warn_s: float, deterministic: bool = False):
        self.handles = _core.NativeHandles()
        self._group_codes: Dict[Tuple, int] = {}
        self._sched = _core.NativeScheduler(
            self._on_batch, cycle_ms=cycle_ms, fusion_bytes=fusion_bytes,
            stall_warn_s=stall_warn_s, deterministic=deterministic)

    def _code(self, key: Tuple) -> int:
        # The native scheduler groups by an int "dtype" code; fold every
        # attribute that must be uniform within a fused dispatch into it.
        with _lock:
            return self._group_codes.setdefault(key, len(self._group_codes))

    def enqueue(self, tensor: torch.Tensor, name: str, op, compression,
                process_set, prescale_factor: float = 1.0,
                postscale_factor: float = 1.0) -> int:
        h = self.handles.create()
        code = self._code((str(tensor.dtype), id(op), id(compression),
                           id(process_set), prescale_factor,
                           postscale_factor))
        payload = (h, tensor, op, compression, process_set,
                   prescale_factor, postscale_factor)
        self._sched.enqueue(payload, name=name, dtype_code=code,
                            nbytes=tensor.numel() * tensor.element_size(),
                            handle=h)
        return h

    def _on_batch(self, payloads: List) -> None:
        # Runs on the native cycle thread (ctypes holds the GIL here).
        try:
            from . import grouped_allreduce
            tensors = [p[1] for p in payloads]
            _, _, op, compression, process_set, pre, post = payloads[0]
            outs = grouped_allreduce(tensors, op=op,
                                     compression=compression,
                                     process_set=process_set,
                                     prescale_factor=pre,
                                     postscale_factor=post,
                                     name="cycle_fused")
            for (h, t, *_), o in zip(payloads, outs):
                t.copy_(o)
                self.handles.done(h, 0)
        except Exception as e:  # noqa: BLE001 - propagate via handles
            for p in payloads:
                self.handles.done(p[0], 1, f"{type(e).__name__}: {e}")

    def wait(self, h: int, timeout_s: float = 300.0) -> None:
        self._sched.flush()
        status = self.handles.wait(h, timeout_s)
        err = self.handles.error(h) if status not in (0, -2, -3) else ""
        self.handles.release(h)  # always: a leaked entry trips the
        # stall inspector forever and inflates pending() counts
        if status == -2:
            raise HorovodInternalError(
                f"allreduce handle {h} timed out after {timeout_s}s")
        if status not in (0, -3):
            raise HorovodInternalError(
                f"fused allreduce failed: {err or status}")

    def poll(self, h: int) -> bool:
        return self.handles.poll(h) != 0

    def stop(self) -> None:
        self._sched.stop()


def batcher() -> Optional[GradBatcher]:
    """The process-wide batcher, started lazily; None if native core is
    unavailable."""
    global _batcher
    with _lock:
        if _batcher is not None:
            return _batcher
        if not _core.available():
            return None
        cfg = global_state().config
        cycle_ms = getattr(cfg, "cycle_time", 1.0)
        stall = 0.0 if cfg.stall_check_disable else cfg.stall_check_time
        # Multi-controller SPMD: every process must cut identical fused
        # batches (they jointly launch each XLA program), so the scheduler
        # runs in deterministic mode UNCONDITIONALLY there -- it is a
        # correctness requirement, not a knob.  Also deterministic on
        # accelerator backends even single-process: timing-based cutting
        # produces DIFFERENT fused shapes each cycle, and every new shape
        # is a fresh XLA compile -- seconds per step on the tunnelled TPU
        # vs. ms on CPU.  HOROVOD_DETERMINISTIC=0/1 overrides only the
        # single-process backend heuristic.
        import os

        import jax
        from ..core.config import _env_bool
        if ("HOROVOD_DETERMINISTIC" in os.environ
                or "HVD_TPU_DETERMINISTIC" in os.environ):
            single_proc_det = _env_bool("DETERMINISTIC", False)
        else:
            single_proc_det = jax.default_backend() != "cpu"
        deterministic = jax.process_count() > 1 or single_proc_det
        _batcher = GradBatcher(cycle_ms, cfg.fusion_threshold, stall,
                               deterministic=deterministic)
        atexit.register(shutdown_batcher)
        return _batcher


def shutdown_batcher() -> None:
    global _batcher
    with _lock:
        b, _batcher = _batcher, None
    if b is not None:
        b.stop()
